"""Token-level decode serving: `GenerationSession`.

`ServeEngine` serves request-shaped functions — every call re-runs the
whole forward.  For autoregressive generation that is O(T^2) attention
flops per sequence; the KV cache makes each token O(T).  This module is
the serving half of the cache-carrying model API
(models/gpt.py::gpt_prefill_chunk/gpt_decode_step and the llama mirror):

  * **chunked, batched prefill** — each admitted prompt is processed in
    fixed [prefill_batch, prefill_chunk] windows against a multi-row
    staging cache, so ONE compiled prefill signature per bucket serves
    every prompt length (PR 9 compiled one per pow2-padded length), and
    up to `prefill_batch` pending prompts share each chunk call;
  * **prefix-reuse KV cache** — finished prefills commit their aligned
    KV chunks into a per-bucket token trie (serve/prefix_cache.py);
    admission restores the longest cached whole-chunk prefix with
    `dynamic_update_slice` and resumes prefill at `prefix_len` instead
    of 0.  Restored and recomputed KV are bitwise identical, so the
    cache is a pure latency optimization (`enable_prefix_cache=False`
    produces bitwise-identical outputs);
  * **bounded prefill pressure** — `step()` interleaves at most
    `prefill_chunks_per_step` chunk calls before the decode rounds run,
    so a long prompt cannot stall in-flight decodes for its whole
    prefill (decode p99 stays bounded);
  * **bucketed KV pool + one compiled decode step** — unchanged from
    PR 9: one slot pool per `ServeConfig.decode_buckets` entry, decode
    always steps ALL slots, slots recycle through a free list;
  * **paged KV pool** (`ServeConfig.kv_layout="paged"`) — ALL buckets
    collapse into ONE page-granular pool over a preallocated arena
    (kv/pool.py + kv/table.py): sequences of any length share one
    compiled decode step (the int32 page table, fixed
    [max_slots, max_pages], is the only per-step state that varies), a
    restored prefix is table entries pointing at trie-committed pages
    (zero copies — the bucketed path `dynamic_update_slice`-copies every
    restored chunk), and prefill writes arena pages directly through the
    table (no staging cache, no migrate).  Admission reserves every page
    a sequence can ever touch up front, so the table row is static for
    the slot's life; analyze rule KV001 audits the refcount/table
    bookkeeping at first decode and every retire;
  * **donated caches** — pool and staging are positional arg 0 and
    output 0 of their compiled callables, so `infer_state_io` pairs and
    donates them; XLA updates in place instead of copying.  `analyze`
    rules SERVE001 (decode) and SERVE002 (chunked prefill: donation +
    length-masked attention + trie accounting) audit exactly this.

Sharding rides the existing solver: the cache's heads axis (dim 2) is the
tensor-parallel shard dim, matching the attention strategy the solver
picks for the model itself, so tp serving works unchanged —
`kv_cache_specs` names the placement for callers that want to lay the
pool out explicitly.
"""

from __future__ import annotations

import collections
import logging
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from easydist_tpu.kv import PagePool, PageTable, is_host_ref, is_page_ref
from easydist_tpu.kv.tier import HostTier, TierError
from easydist_tpu.resilience import faultinject

from .admission import ReplicaDrainingError, RequestTooLargeError
from .batcher import select_bucket
from .engine import ServeConfig
from .metrics import ServeMetrics
from .prefix_cache import PrefixCache
from .speculate import NGramDrafter, accept_length

logger = logging.getLogger(__name__)


# process-level memo of compiled step functions, keyed by (model identity,
# mesh).  Every compiled callable below is pure — cache, params, and tokens
# all cross as arguments — so sessions over the same model/mesh can share
# the traced-and-XLA-compiled programs instead of each replica re-paying
# the compile.  This is the fleet case: N in-process replicas differ only
# in the state they carry, never in the program they run.
_COMPILED_MEMO: Dict[tuple, tuple] = {}

# adaptive-speculation throttle: a verify row costs ~1.5x a decode row,
# so drafting pays off only while the stream's recent accepted-tokens-
# per-round stays above the break-even (~0.5).  Each request carries an
# EWMA of its accept counts; below the floor it stops drafting and only
# PROBES every _SPEC_PROBE_EVERY scheduling rounds, so a stream the
# drafter cannot predict decays to plain decode (one speculative probe
# per 12 rounds ~ the whole adversarial overhead) while a stream that
# turns predictable again is rediscovered within one probe interval.
_SPEC_EWMA_ALPHA = 0.3
_SPEC_EWMA_FLOOR = 0.5
_SPEC_PROBE_EVERY = 12
# full-batch verify economics: the verify program is k+1 positions wide
# for EVERY row, drafted or not, so a round beats a decode round only
# when the drafting rows' expected accepts cover the whole batch's share
# of the wider program: sum(ewma) > (cost - 1) * rows.  Rounds that
# close below that line pause speculation for a probe interval.
_SPEC_VERIFY_COST = 2.0


def kv_cache_specs(axis: str = "tp"):
    """PartitionSpec pytree for a KV cache {"k", "v"} of shape
    [layers, batch/slots, heads, max_len, head_dim]: heads sharded on
    `axis`, everything else replicated — the placement consistent with a
    tensor-parallel attention strategy."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis, None, None)
    return {"k": spec, "v": spec}


@dataclass
class _Slot:
    """Host-side view of one pooled decode row."""
    request_id: int
    future: Future
    pos: int                      # next cache write position
    token: int                    # last generated token (not yet in cache)
    max_new: int
    eos_id: Optional[int]
    generated: List[int] = field(default_factory=list)
    pinned: List[object] = field(default_factory=list)  # trie nodes held
    prompt: List[int] = field(default_factory=list)  # for evacuation


@dataclass
class _PrefillJob:
    """One prompt mid-prefill: owns a staging row and a reserved pool
    slot; `start` advances one chunk per batched chunk call."""
    request_id: int
    future: Future
    prompt: List[int]
    max_new: int
    eos_id: Optional[int]
    row: int                      # staging row
    slot_idx: int                 # reserved pool slot
    start: int                    # next chunk start (multiple of chunk)
    prefix_nodes: List[object]    # trie nodes restored (pinned)
    t_submit: float


class _BucketPool:
    """One decode bucket: pooled cache + free-list slot allocator +
    multi-row staging cache shared by the chunked-prefill scheduler +
    the bucket's prefix trie."""

    def __init__(self, bucket: int, n_slots: int, init_cache,
                 n_rows: int = 1, chunk: int = 0,
                 prefix_bytes: int = 0):
        self.bucket = bucket
        self.n_slots = n_slots
        self.cache = init_cache(n_slots, bucket)
        self.n_rows = n_rows
        self.staging = init_cache(n_rows, bucket)
        self.chunk = chunk                      # 0 = legacy one-shot path
        self.free: List[int] = list(range(n_slots))
        self.slots: Dict[int, _Slot] = {}          # slot index -> _Slot
        self.free_rows: List[int] = list(range(n_rows))
        self.jobs: Dict[int, _PrefillJob] = {}     # staging row -> job
        self.trie: Optional[PrefixCache] = \
            PrefixCache(chunk, prefix_bytes) if chunk and prefix_bytes \
            else None

    @property
    def n_active(self) -> int:
        return len(self.slots)


class _PagedPool:
    """The paged layout's single pool: one preallocated page arena, a
    refcounted page allocator, and a fixed [n_slots, max_pages] page
    table shared by every request regardless of length (`bucket` is the
    capacity cap — max(decode_buckets) — not a padding granularity).
    Prefill jobs write arena pages directly through the table, so there
    is no staging cache and no migrate; a restored prefix is table
    entries pointing at trie-committed pages (zero-copy)."""

    def __init__(self, bucket: int, n_slots: int, init_pages,
                 n_rows: int, chunk: int, prefix_bytes: int,
                 n_pages: int, host_tier_bytes: int = 0,
                 export_page: Optional[Callable] = None,
                 model_itemsize: int = 0):
        self.bucket = bucket
        self.n_slots = n_slots
        self.chunk = chunk                       # page_tokens
        self.max_pages = bucket // chunk
        if n_pages < self.max_pages:
            raise ValueError(
                f"kv_arena_pages {n_pages} cannot hold even one "
                f"full-length sequence ({self.max_pages} pages)")
        self.n_rows = n_rows
        self.arena = init_pages(n_pages, chunk)
        # size pages from the arena's STORAGE leaves — quantized arenas
        # charge int8 payload + f32 scales, not the model dtype, which is
        # exactly the density win the kv_quant_bytes_saved gauge reports
        self.page_bytes = sum(int(self.arena[k].nbytes) // n_pages
                              for k in self.arena)
        # what one page's k/v payload would cost at model precision —
        # the baseline the quant-savings gauge subtracts from
        payload_elems = sum(int(self.arena[k].size) // n_pages
                            for k in ("k", "v"))
        self.model_page_bytes = payload_elems * model_itemsize \
            if model_itemsize else self.page_bytes
        self.pool = PagePool(n_pages, chunk, page_bytes=self.page_bytes)
        self.table = PageTable(n_slots, self.max_pages, n_pages)
        self.free: List[int] = list(range(n_slots))
        self.slots: Dict[int, _Slot] = {}
        self.free_rows: List[int] = list(range(n_rows))
        self.jobs: Dict[int, _PrefillJob] = {}
        self.trie: Optional[PrefixCache] = \
            PrefixCache(chunk, prefix_bytes,
                        on_evict=self._release_evicted) \
            if prefix_bytes else None
        # host tier (kv/tier.py): demotion target for cold trie pages;
        # `export_page(pool, pid)` is the session's compiled single-page
        # arena read (the same program fleet export uses)
        self.tier: Optional[HostTier] = \
            HostTier(host_tier_bytes) \
            if host_tier_bytes and self.trie is not None else None
        self._export_page = export_page
        self._tier_seq = 0

    def _release_evicted(self, node) -> None:
        # trie eviction drops the trie's hold on the node's arena page;
        # the page only frees when no live slot still maps it.  A node
        # already demoted to the host tier owns no arena page — evicting
        # it just forgets the host copy.
        if is_host_ref(node.kv):
            if self.tier is not None:
                self.tier.drop(node.kv["host"])
            return
        self.pool.release(node.kv["page"])

    @property
    def n_active(self) -> int:
        return len(self.slots)

    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        """Worst-case pages one sequence touches: prefill writes
        ceil(prompt/chunk) whole pages, decode writes up to
        `max_new - 1` more positions, everything capped at the bucket
        (retirement fires at pos >= bucket)."""
        cap = min(self.bucket, prompt_len + max_new)
        return -(-cap // self.chunk)

    def make_room(self, n_pages: int) -> bool:
        """Free arena pages until `n_pages` are available, evicting
        unpinned trie nodes LRU-first (an eviction only yields a free
        page when no live slot shares it).  Returns availability.

        With a host tier configured, demotion runs FIRST: the coldest
        unpinned device-page node moves its bytes to host and keeps its
        trie position (the prefix survives HBM pressure).  Only when the
        tier refuses (paused after host_oom, budget exhausted, nothing
        demotable) does plain eviction run — and then only against
        device-page nodes, because evicting a host-ref node frees no
        arena page and would pointlessly discard tiered bytes."""
        if self.trie is not None:
            while self.pool.n_free < n_pages:
                if self.tier is not None:
                    if not self.tier.paused and self._demote_one():
                        continue
                    victim = self.trie.lru_node(
                        lambda n: not n.children and is_page_ref(n.kv))
                    if victim is None \
                            or not self.trie.evict_node(victim):
                        break
                elif not self.trie.evict_lru():
                    break
        return self.pool.n_free >= n_pages

    def _demote_one(self) -> bool:
        """Demote the LRU unpinned device-page trie node to the host
        tier: export the page's arrays, `tier.put` (chunked fetch +
        manifest), swap the node's kv to `{"host": key}` at 0 trie
        bytes, release the arena page.  Returns False when nothing is
        demotable or the tier refused the bytes (caller falls back to
        eviction)."""
        node = self.trie.lru_node(lambda n: is_page_ref(n.kv))
        if node is None or self._export_page is None:
            return False
        pid = node.kv["page"]
        key = ("pg", self._tier_seq)
        self._tier_seq += 1
        if not self.tier.put(key, self._export_page(self, pid)):
            return False
        self.trie.reaccount(node, 0, kv={"host": key})
        self.pool.release(pid)
        return True

    def occupancy(self):
        """(pages_in_use, real tokens held) for the kv gauges: slots
        hold `pos` cached tokens, jobs `start` (restored + prefilled so
        far), trie-only pages a whole chunk each; reserved-but-unwritten
        pages count capacity only — that gap IS the fragmentation the
        `kv_page_utilization` gauge measures."""
        tokens = sum(min(s.pos, self.bucket) for s in self.slots.values())
        tokens += sum(j.start for j in self.jobs.values())
        if self.trie is not None:
            mapped = set()
            for idx in self.slots:
                mapped.update(self.table.mapped(idx))
            for job in self.jobs.values():
                mapped.update(self.table.mapped(job.slot_idx))
            for node in self.trie._walk():
                pid = node.kv.get("page") \
                    if isinstance(node.kv, dict) else None
                if pid is not None and pid not in mapped:
                    mapped.add(pid)  # host-ref nodes hold no arena page
                    tokens += self.chunk
        return self.pool.in_use, tokens


class GenerationSession:
    """Continuous-batching token generation over a cache-carrying model.

    model_prefill(params, cache, tokens, lengths) -> (cache, logits)
    model_decode(params, cache, token, pos) -> (cache, logits)
    model_prefill_chunk(params, cache, tokens, start_pos, lengths)
        -> (cache, logits) — fixed-chunk window at absolute positions;
        enables the chunked/batched/prefix-reuse prefill scheduler (the
        `for_gpt`/`for_llama` constructors wire it; without it the
        session falls back to PR 9's one-shot pow2-padded prefill).
    init_cache(batch, max_len, dtype=None) -> cache pytree

    Greedy decoding (argmax inside the compiled step, so only int32 token
    ids cross the host boundary per token).  `submit` returns a Future
    resolving to {"ids": [...generated ids...], "finish_reason":
    "eos"|"length"|"bucket_full"}; drive with `step()` (admit + bounded
    prefill chunks + decode + harvest) or `run_until_drained()`.

    `compile_key` (any hashable; `for_gpt`/`for_llama` derive one from the
    model config) opts the session into the process-level compiled-program
    memo: replicas over the same model and mesh share traced/compiled step
    functions instead of each paying the compile — the callables are pure,
    so only host-side state is per-session.
    """

    def __init__(self, params, *, model_prefill: Callable,
                 model_decode: Callable, init_cache: Callable,
                 model_prefill_chunk: Optional[Callable] = None,
                 model_prefill_chunk_paged: Optional[Callable] = None,
                 model_decode_paged: Optional[Callable] = None,
                 model_verify: Optional[Callable] = None,
                 model_verify_paged: Optional[Callable] = None,
                 init_pages: Optional[Callable] = None,
                 drafter: Optional[object] = None,
                 config: Optional[ServeConfig] = None, mesh=None,
                 eos_id: Optional[int] = None,
                 max_prompt_len: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None,
                 replica_id: Optional[str] = None,
                 compile_key: Optional[object] = None):
        from easydist_tpu.jaxfront import easydist_compile

        self.config = config or ServeConfig()
        self.replica_id = replica_id
        if max_prompt_len is not None:
            bad = [b for b in self.config.decode_buckets
                   if b > max_prompt_len]
            if bad:
                raise ValueError(
                    f"decode_buckets {bad} exceed the model's maximum "
                    f"sequence length {max_prompt_len}; set "
                    f"ServeConfig(decode_buckets=...) within it")
        self.params = params
        self.mesh = mesh
        self.eos_id = eos_id
        self.metrics = metrics or ServeMetrics(replica_id=replica_id)
        self._draining = False
        self._closed = False
        self._init_cache = init_cache
        self._chunked = model_prefill_chunk is not None
        self._paged = self.config.kv_layout == "paged"
        if self._paged and (model_prefill_chunk_paged is None
                            or model_decode_paged is None
                            or init_pages is None):
            raise ValueError(
                "kv_layout='paged' requires model_prefill_chunk_paged, "
                "model_decode_paged, and init_pages (the for_gpt/"
                "for_llama constructors wire all three)")
        self._init_pages = init_pages
        self._pending: collections.deque = collections.deque()
        self._pools: Dict[int, _BucketPool] = {}
        self._next_request_id = 0
        self._audited: set = set()
        self._audited_prefill: set = set()
        self._audited_verify: set = set()

        # speculative decoding (serve/speculate.py): a drafter proposes k
        # tokens, one verify step scores all k+1 positions, the session
        # commits the longest self-validating prefix.  Pure speed knob —
        # committed tokens are exactly the plain-greedy stream.
        self._spec_k = int(self.config.speculate_k or 0)
        self._drafter = None
        if self._spec_k:
            if self._paged and model_verify_paged is None:
                raise ValueError(
                    "speculate_k with kv_layout='paged' requires "
                    "model_verify_paged (the for_gpt/for_llama "
                    "constructors wire it)")
            if not self._paged and model_verify is None:
                raise ValueError(
                    "speculate_k requires model_verify (the for_gpt/"
                    "for_llama constructors wire it)")
            if drafter is not None:
                self._drafter = drafter
            elif self.config.speculate_drafter == "ngram":
                self._drafter = NGramDrafter()
            else:
                raise ValueError(
                    "speculate_drafter='draft_model' needs an explicit "
                    "drafter: pass drafter=..., or draft_model="
                    "(params, cfg) to for_gpt/for_llama")
        # adaptive speculation (module constants above): per-request
        # accept-rate EWMA + probe counter.  Purely a scheduling knob —
        # which rounds verify never changes the committed tokens (the
        # accept rule is self-validating), so parity and crash-resume
        # stay bitwise.
        self._spec_ewma: Dict[int, float] = {}
        self._spec_idle: Dict[int, int] = {}
        self._spec_gate_idle = 0

        def _prefill(cache, params, tokens, lengths):
            import jax.numpy as jnp

            cache, logits = model_prefill(params, cache, tokens, lengths)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _prefill_chunk(staging, params, tokens, start, lengths):
            import jax.numpy as jnp

            staging, logits = model_prefill_chunk(params, staging, tokens,
                                                  start, lengths)
            return staging, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _restore(staging, chunk_kv, row, start):
            import jax

            return {
                k: jax.lax.dynamic_update_slice(
                    staging[k],
                    chunk_kv[k][:, None].astype(staging[k].dtype),
                    (0, row, 0, start, 0))
                for k in ("k", "v")
            }

        def _migrate(pool, staging, row, slot):
            import jax

            out = {}
            for k in ("k", "v"):
                layers, _, heads, max_len, hd = staging[k].shape
                src = jax.lax.dynamic_slice(
                    staging[k], (0, row, 0, 0, 0),
                    (layers, 1, heads, max_len, hd))
                out[k] = jax.lax.dynamic_update_slice(
                    pool[k], src.astype(pool[k].dtype), (0, slot, 0, 0, 0))
            return out

        def _decode(pool, params, token, pos):
            import jax.numpy as jnp

            pool, logits = model_decode(params, pool, token, pos)
            return pool, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # speculative verify: tokens is [slots, k+1] (committed token then
        # k drafts), the program writes K/V at all k+1 positions and
        # returns the greedy pick at EVERY position — the commit walk
        # happens on the host over int32 ids only
        def _verify(pool, params, tokens, pos):
            import jax.numpy as jnp

            pool, logits = model_verify(params, pool, tokens, pos)
            return pool, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._verify_def = _verify if model_verify is not None else None

        # paged-layout programs: arena first for donation pairing, the
        # int32 page table crosses as data every call (fixed shape — the
        # signature stays closed over arbitrary per-row lengths).
        # Compiled lazily via `_paged_c` so bucketed sessions never pay
        # for them; export/import move single pages for fleet handoff.
        def _prefill_chunk_paged(arena, params, table, tokens, start,
                                 lengths):
            import jax.numpy as jnp

            arena, logits = model_prefill_chunk_paged(
                params, arena, table, tokens, start, lengths)
            return arena, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _decode_paged(arena, params, table, token, pos):
            import jax.numpy as jnp

            arena, logits = model_decode_paged(params, arena, table,
                                               token, pos)
            return arena, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # export/import iterate ALL arena keys: a quantized arena ships
        # its scale leaves alongside the int8 payload, so fleet manifests
        # (and host-tier manifests) cover both — a scale/payload desync
        # cannot pass a digest check.  Exact arenas have keys {"k","v"},
        # so the quant-off jaxpr is unchanged.
        def _page_export(arena, page):
            import jax

            return {k: jax.lax.dynamic_index_in_dim(
                        arena[k], page, axis=1, keepdims=False)
                    for k in arena}

        def _page_import(arena, chunk_kv, page):
            import jax

            return {k: jax.lax.dynamic_update_index_in_dim(
                        arena[k], chunk_kv[k].astype(arena[k].dtype),
                        page, axis=1)
                    for k in arena}

        def _verify_paged(arena, params, table, tokens, pos):
            import jax.numpy as jnp

            arena, logits = model_verify_paged(params, arena, table,
                                               tokens, pos)
            return arena, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._paged_defs = (
            {"chunk": _prefill_chunk_paged, "decode": _decode_paged,
             "export": _page_export, "import": _page_import}
            if model_prefill_chunk_paged is not None else {})
        if model_verify_paged is not None:
            self._paged_defs["verify"] = _verify_paged

        # pool/staging is arg 0 and output 0 of every mutating compiled
        # callable, so state_io="auto" pairs it and XLA gets the buffer
        # donated; _extract's output is chunk-shaped (no pairing, no
        # donation — it must not invalidate the staging it reads)
        # `mesh=None` means "the global mesh at first call", which is
        # sticky process state that can change between sessions — resolve
        # it NOW so every program this session runs (and every session
        # sharing this memo entry) is compiled against the same mesh.
        # Unresolvable (no global installed yet) skips the memo: the
        # session compiles privately under whatever ambient its first
        # call sees, exactly the pre-memo behavior.
        if mesh is None:
            from easydist_tpu.jaxfront.mesh import get_device_mesh

            mesh = get_device_mesh()
            self.mesh = mesh  # _extract_for compiles against it too
        memo_key = (compile_key, mesh) \
            if compile_key is not None and mesh is not None else None
        shared = _COMPILED_MEMO.get(memo_key) if memo_key else None
        if shared is None:
            shared = (easydist_compile(_prefill, mesh=mesh),
                      easydist_compile(_prefill_chunk, mesh=mesh),
                      easydist_compile(_restore, mesh=mesh),
                      easydist_compile(_migrate, mesh=mesh),
                      easydist_compile(_decode, mesh=mesh),
                      {}, {}, {})
            if memo_key:
                while len(_COMPILED_MEMO) >= 32:  # live sessions keep refs
                    _COMPILED_MEMO.pop(next(iter(_COMPILED_MEMO)))
                _COMPILED_MEMO[memo_key] = shared
        (self._prefill_c, self._prefill_chunk_c, self._restore_c,
         self._migrate_c, self._decode_c, self._extract_cs,
         self._paged_cs, self._verify_cs) = shared

    def _extract_for(self, chunk_len: int) -> Callable:
        """Compiled chunk extractor for one chunk size (the slice size
        must be static, so each chunk length is its own closure — one per
        distinct bucket chunk, compiled once)."""
        fn = self._extract_cs.get(chunk_len)
        if fn is None:
            from easydist_tpu.jaxfront import easydist_compile

            def _extract(staging, row, start):
                import jax

                out = {}
                for k in ("k", "v"):
                    layers, _, heads, _, hd = staging[k].shape
                    out[k] = jax.lax.dynamic_slice(
                        staging[k], (0, row, 0, start, 0),
                        (layers, 1, heads, chunk_len, hd))[:, 0]
                return out

            fn = easydist_compile(_extract, mesh=self.mesh)
            self._extract_cs[chunk_len] = fn
        return fn

    def _paged_c(self, name: str) -> Callable:
        """Compiled paged program ("chunk" / "decode" / "export" /
        "import"), built on first use and shared through the process
        memo exactly like `_extract_for`."""
        fn = self._paged_cs.get(name)
        if fn is None:
            from easydist_tpu.jaxfront import easydist_compile

            fn = easydist_compile(self._paged_defs[name], mesh=self.mesh)
            self._paged_cs[name] = fn
        return fn

    def _verify_c(self) -> Callable:
        """Compiled bucketed verify step, built on first use and shared
        through the process memo exactly like `_paged_c` (the paged
        layout's verify program lives in `_paged_defs`/`_paged_cs`)."""
        fn = self._verify_cs.get("verify")
        if fn is None:
            from easydist_tpu.jaxfront import easydist_compile

            fn = easydist_compile(self._verify_def, mesh=self.mesh)
            self._verify_cs["verify"] = fn
        return fn

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> Future:
        """Queue one prompt; generation interleaves with every other live
        request (continuous batching) as `step()` is driven."""
        if self._draining or self._closed:
            raise ReplicaDrainingError(
                f"session{f' {self.replica_id}' if self.replica_id else ''} "
                f"is {'closed' if self._closed else 'draining'}: in-flight "
                f"work retires but nothing new is admitted")
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if select_bucket(len(prompt) + 1, self.config.decode_buckets) is None:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens does not fit any decode "
                f"bucket {self.config.decode_buckets} with room to "
                f"generate")
        fut = Future()
        self._pending.append(
            (prompt, max_new_tokens,
             self.eos_id if eos_id is None else eos_id, fut,
             time.perf_counter()))
        self.metrics.inc("requests_submitted")
        self.metrics.set_gauge("queue_depth", self.queue_depth)
        return fut

    @property
    def queue_depth(self) -> int:
        """Live requests this session owns: queued + prefilling + decoding
        (the fleet router's occupancy signal)."""
        return len(self._pending) + sum(
            len(p.jobs) + p.n_active for p in self._pools.values())

    # ------------------------------------------------------------- plumbing
    def _pool_for(self, bucket: int):
        cfg = self.config
        if self._paged:
            # every bucket collapses into the one page-granular pool:
            # lengths are a page-table concern, not a compile-signature
            # concern, so there is nothing to bucket by
            bucket = max(cfg.decode_buckets)
        pool = self._pools.get(bucket)
        if pool is None:
            if self._paged:
                chunk = cfg.kv_page_tokens or min(cfg.prefill_chunk,
                                                  bucket)
                max_pages = bucket // chunk
                n_pages = cfg.kv_arena_pages or \
                    (cfg.max_decode_slots + 1) * max_pages
                pool = _PagedPool(
                    bucket, cfg.max_decode_slots, self._pages_factory,
                    n_rows=cfg.prefill_batch, chunk=chunk,
                    prefix_bytes=(cfg.prefix_cache_bytes
                                  if cfg.enable_prefix_cache else 0),
                    n_pages=n_pages,
                    host_tier_bytes=cfg.kv_host_tier_bytes,
                    export_page=self._export_arena_page,
                    model_itemsize=self._model_itemsize())
            elif self._chunked:
                pool = _BucketPool(
                    bucket, cfg.max_decode_slots, self._cache_factory,
                    n_rows=cfg.prefill_batch,
                    chunk=min(cfg.prefill_chunk, bucket),
                    prefix_bytes=(cfg.prefix_cache_bytes
                                  if cfg.enable_prefix_cache else 0))
            else:
                pool = _BucketPool(bucket, cfg.max_decode_slots,
                                   self._cache_factory)
            self._pools[bucket] = pool
        return pool

    def _cache_factory(self, batch: int, max_len: int):
        dtype = self.config.kv_cache_dtype
        return self._init_cache(batch, max_len,
                                None if dtype == "auto" else dtype)

    def _pages_factory(self, n_pages: int, page_tokens: int):
        cfg = self.config
        dtype = None if cfg.kv_cache_dtype == "auto" else cfg.kv_cache_dtype
        if cfg.kv_quant_dtype != "none":
            # quant kwargs only when armed, so custom init_pages lambdas
            # predating the knob keep working for quant-off sessions
            return self._init_pages(n_pages, page_tokens, dtype,
                                    quant_dtype=cfg.kv_quant_dtype,
                                    quant_block=cfg.kv_quant_block)
        return self._init_pages(n_pages, page_tokens, dtype)

    def _model_itemsize(self) -> int:
        """Bytes per element at model precision (first param leaf) — the
        baseline kv_quant_bytes_saved subtracts the arena's actual
        storage cost from."""
        import jax

        leaves = jax.tree_util.tree_leaves(self.params)
        return int(np.dtype(leaves[0].dtype).itemsize) if leaves else 0

    def _export_arena_page(self, pool, pid: int):
        """Compiled single-page arena read (the fleet-export program) —
        the host tier's demotion source."""
        import jax.numpy as jnp

        return self._paged_c("export")(pool.arena,
                                       jnp.asarray(int(pid), jnp.int32))

    def _prefill_pad(self, plen: int, bucket: int) -> int:
        """Legacy one-shot path: smallest power of two >= plen (floor 8),
        capped at the decode bucket."""
        t = 8
        while t < plen:
            t *= 2
        return min(t, bucket)

    def _admit_one(self) -> bool:
        """Pop one pending request toward generation.  Chunked path:
        reserve a pool slot + staging row, restore the longest cached
        prefix, and enqueue a prefill job (chunks run in `step()`).
        Legacy path: one-shot prefill + migrate, as in PR 9.  Returns
        False when nothing is admissible."""
        import jax.numpy as jnp

        if not self._pending:
            return False
        prompt, max_new, eos, fut, t_submit = self._pending[0]
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pool_for(bucket)
        if not pool.free:
            return False
        if (self._chunked or self._paged) and not pool.free_rows:
            return False
        if self._paged:
            return self._admit_one_paged(pool)
        self._pending.popleft()
        if fut.set_running_or_notify_cancel() is False:
            return True  # cancelled while queued; slot stays free
        slot_idx = pool.free.pop()

        if self._chunked:
            row = pool.free_rows.pop()
            prefix_len, nodes = 0, []
            if pool.trie is not None:
                # cap below len(prompt): at least one real token must run
                # through prefill so the finishing chunk produces logits
                prefix_len, nodes = pool.trie.match(
                    prompt, max_tokens=len(prompt) - 1)
                for j, node in enumerate(nodes):
                    pool.staging = self._restore_c(
                        pool.staging, node.kv,
                        jnp.asarray(row, jnp.int32),
                        jnp.asarray(j * pool.chunk, jnp.int32))
                pool.trie.pin(nodes)
            self.metrics.record_admission(len(prompt), prefix_len)
            pool.jobs[row] = _PrefillJob(
                request_id=self._next_request_id, future=fut,
                prompt=prompt, max_new=max_new, eos_id=eos, row=row,
                slot_idx=slot_idx, start=prefix_len,
                prefix_nodes=nodes, t_submit=t_submit)
            self._next_request_id += 1
            return True

        t_pad = self._prefill_pad(len(prompt), bucket)
        tokens = np.full((1, t_pad), int(self.config.pad_value), np.int32)
        tokens[0, :len(prompt)] = prompt
        lengths = np.array([len(prompt)], np.int32)
        pool.staging, first = self._prefill_c(
            pool.staging, self.params, jnp.asarray(tokens),
            jnp.asarray(lengths))
        pool.cache = self._migrate_c(pool.cache, pool.staging,
                                     jnp.asarray(0, jnp.int32),
                                     jnp.asarray(slot_idx, jnp.int32))
        self.metrics.record_admission(len(prompt), 0)
        self.metrics.observe("ttft", time.perf_counter() - t_submit)

        slot = _Slot(request_id=self._next_request_id, future=fut,
                     pos=len(prompt), token=int(np.asarray(first)[0]),
                     max_new=max_new, eos_id=eos, prompt=prompt)
        self._next_request_id += 1
        slot.generated.append(slot.token)
        pool.slots[slot_idx] = slot
        self._maybe_retire(pool, slot_idx)
        return True

    def _admit_one_paged(self, pool: _PagedPool) -> bool:
        """Paged admission: reserve EVERY page the sequence can ever
        touch up front (decode crossing a page boundary must find the
        page already mapped — a sentinel there silently drops the
        token's K/V), mapping the trie's committed prefix pages in place
        of the bucketed layout's restore copies.  Defers (returns False,
        request stays queued) when the arena cannot make room."""
        prompt, max_new, eos, fut, t_submit = self._pending[0]
        prefix_len, nodes = 0, []
        if pool.trie is not None:
            # cap below len(prompt): at least one real token must run
            # through prefill so the finishing chunk produces logits
            prefix_len, nodes = pool.trie.match(
                prompt, max_tokens=len(prompt) - 1)
            pool.trie.pin(nodes)  # survive make_room's evictions
            if pool.tier is not None:
                # BEFORE the slot's first decode step: demoted nodes on
                # the matched path come back into arena pages (manifest
                # verified); a tier miss truncates the usable prefix
                nodes, prefix_len = self._promote_path(pool, nodes)
        n_need = pool.pages_needed(len(prompt), max_new)
        if not pool.make_room(n_need - len(nodes)):
            if pool.trie is not None:
                pool.trie.unpin(nodes)
            return False
        self._pending.popleft()
        if fut.set_running_or_notify_cancel() is False:
            if pool.trie is not None:
                pool.trie.unpin(nodes)
            return True  # cancelled while queued; nothing reserved yet
        slot_idx = pool.free.pop()
        row = pool.free_rows.pop()
        # zero-copy restore: the slot's leading windows point at the
        # trie's pages (shared, read-only by construction — writes only
        # land past the prefix); the bucketed path would
        # dynamic_update_slice-copy these bytes into staging here
        for j, node in enumerate(nodes):
            pid = node.kv["page"]
            pool.pool.share(pid)
            pool.table.map(slot_idx, j, pid)
        for j in range(len(nodes), n_need):
            pool.table.map(slot_idx, j, pool.pool.alloc())
        if nodes:
            self.metrics.record_copy_on_restore_saved(
                len(nodes) * pool.page_bytes)
        self.metrics.record_admission(len(prompt), prefix_len)
        pool.jobs[row] = _PrefillJob(
            request_id=self._next_request_id, future=fut, prompt=prompt,
            max_new=max_new, eos_id=eos, row=row, slot_idx=slot_idx,
            start=prefix_len, prefix_nodes=nodes, t_submit=t_submit)
        self._next_request_id += 1
        return True

    def _promote_path(self, pool: _PagedPool, nodes):
        """Promote host-tier refs along a matched (pinned) path back
        into arena pages: `tier.get` manifest-verifies the host bytes,
        the compiled import program uploads them into a fresh page, and
        the node's kv swaps back to `{"page": id}` at full byte cost.
        The round trip moves exact storage bytes (payload AND scales),
        so it is bitwise.  A missing/corrupt entry truncates the usable
        prefix at that node — the tail unpins and prefill recomputes it
        (never serves unverified KV).  Returns (nodes, prefix_len)."""
        import jax.numpy as jnp

        for j, node in enumerate(nodes):
            if is_page_ref(node.kv):
                continue
            key = node.kv["host"]
            try:
                host_kv = pool.tier.get(key)
            except (KeyError, TierError) as e:
                logger.warning("[kv.tier] promotion of %r failed (%s); "
                               "prefix truncated, chunk recomputes", key, e)
                host_kv = None
            if host_kv is None or not pool.make_room(1):
                pool.trie.unpin(nodes[j:])
                return nodes[:j], j * pool.chunk
            pid = pool.pool.alloc()
            pool.arena = self._paged_c("import")(
                pool.arena,
                {k: jnp.asarray(v) for k, v in host_kv.items()},
                jnp.asarray(pid, jnp.int32))
            pool.tier.drop(key)
            pool.trie.reaccount(node, pool.page_bytes, kv={"page": pid})
        return nodes, len(nodes) * pool.chunk

    # ----------------------------------------------------- chunked prefill
    def _prefill_round(self, pool, max_chunks: int) -> int:
        """Run up to `max_chunks` batched chunk calls on `pool`'s staging
        rows; finished jobs commit to the trie, migrate to their slot, and
        free their row.  Returns the number of chunk calls executed."""
        import jax
        import jax.numpy as jnp

        if self._paged:
            return self._prefill_round_paged(pool, max_chunks)
        calls = 0
        c_len = pool.chunk
        while pool.jobs and calls < max_chunks:
            tokens = np.full((pool.n_rows, c_len),
                             int(self.config.pad_value), np.int32)
            start = np.zeros((pool.n_rows,), np.int32)
            lengths = np.ones((pool.n_rows,), np.int32)
            for row, job in pool.jobs.items():
                seg = job.prompt[job.start:job.start + c_len]
                tokens[row, :len(seg)] = seg
                start[row] = job.start
                lengths[row] = len(job.prompt)
            args = (pool.staging, self.params, jnp.asarray(tokens),
                    jnp.asarray(start), jnp.asarray(lengths))
            result = self._prefill_chunk_c.get_compiled(*args)
            if pool.bucket not in self._audited_prefill:
                self._audited_prefill.add(pool.bucket)
                self._audit_chunked_prefill(result, pool.bucket)
            t0 = time.perf_counter()
            pool.staging, first = result.tree_jitted(*args)
            first = np.asarray(jax.block_until_ready(first))
            self.metrics.record_prefill_chunk(
                pool.n_rows, c_len, time.perf_counter() - t0)
            calls += 1
            for row in list(pool.jobs):
                job = pool.jobs[row]
                job.start += c_len
                if job.start >= len(job.prompt):
                    self._finish_prefill(pool, row, int(first[row]))
        return calls

    def _prefill_round_paged(self, pool: _PagedPool,
                             max_chunks: int) -> int:
        """Paged `_prefill_round`: each chunk writes straight into the
        arena through the job's table row (no staging, no migrate, and a
        restored prefix needed no copy to begin with).  Idle rows get an
        all-sentinel table row so their writes drop and their logits are
        garbage nobody reads — one compiled signature regardless of
        which rows are live."""
        import jax
        import jax.numpy as jnp

        calls = 0
        c_len = pool.chunk
        while pool.jobs and calls < max_chunks:
            tokens = np.full((pool.n_rows, c_len),
                             int(self.config.pad_value), np.int32)
            start = np.zeros((pool.n_rows,), np.int32)
            lengths = np.ones((pool.n_rows,), np.int32)
            tbl = np.full((pool.n_rows, pool.max_pages),
                          pool.pool.sentinel, np.int32)
            for row, job in pool.jobs.items():
                seg = job.prompt[job.start:job.start + c_len]
                tokens[row, :len(seg)] = seg
                start[row] = job.start
                lengths[row] = len(job.prompt)
                tbl[row] = pool.table.array[job.slot_idx]
            args = (pool.arena, self.params, jnp.asarray(tbl),
                    jnp.asarray(tokens), jnp.asarray(start),
                    jnp.asarray(lengths))
            result = self._paged_c("chunk").get_compiled(*args)
            if pool.bucket not in self._audited_prefill:
                self._audited_prefill.add(pool.bucket)
                # SERVE002's jaxpr walk asserts the bucketed staging
                # idiom (dynamic_update_slice restore); the paged
                # program replaces it with table writes, audited
                # host-side by KV001 — only the donation half applies
                try:
                    from easydist_tpu.analyze import check_decode_donation

                    check_decode_donation(
                        result,
                        node=f"prefill_chunk_paged[cap={pool.bucket}]")
                except ImportError:
                    pass
            t0 = time.perf_counter()
            pool.arena, first = result.tree_jitted(*args)
            first = np.asarray(jax.block_until_ready(first))
            self.metrics.record_prefill_chunk(
                pool.n_rows, c_len, time.perf_counter() - t0)
            calls += 1
            for row in list(pool.jobs):
                job = pool.jobs[row]
                job.start += c_len
                if job.start >= len(job.prompt):
                    self._finish_prefill_paged(pool, row,
                                               int(first[row]))
        return calls

    def _finish_prefill_paged(self, pool: _PagedPool, row: int,
                              first_token: int) -> None:
        """One paged job's last chunk ran: commit its whole-chunk pages
        into the trie as page REFERENCES (share + {"page": id} — no
        extraction copy), free the row, open the decode slot."""
        job = pool.jobs.pop(row)
        pinned = list(job.prefix_nodes)
        if pool.trie is not None:
            nodes = list(job.prefix_nodes)
            for j in range(len(nodes), len(job.prompt) // pool.chunk):
                chunk_toks = job.prompt[j * pool.chunk:
                                        (j + 1) * pool.chunk]
                node = pool.trie.lookup_node(nodes, chunk_toks)
                if node is not None and is_host_ref(node.kv):
                    # heal: this prefill just rewrote the chunk's bytes
                    # into a fresh page, so re-point the demoted node at
                    # it (free re-promotion; also recovers nodes whose
                    # tier entry was lost to host LRU eviction)
                    pid = int(pool.table.array[job.slot_idx, j])
                    pool.pool.share(pid)
                    if pool.tier is not None:
                        pool.tier.drop(node.kv["host"])
                    pool.trie.reaccount(node, pool.page_bytes,
                                        kv={"page": pid})
                if node is None:
                    pid = int(pool.table.array[job.slot_idx, j])
                    pool.pool.share(pid)       # the trie's hold
                    node = pool.trie.commit(nodes, chunk_toks,
                                            {"page": pid},
                                            nbytes=pool.page_bytes)
                    if node is None:
                        pool.pool.release(pid)  # budget refused it
                if node is None:
                    break  # byte budget exhausted; partial path is fine
                nodes.append(node)
            pool.trie.unpin(job.prefix_nodes)
            pool.trie.pin(nodes)
            pinned = nodes
            self._audit_prefix_cache(pool)
        pool.free_rows.append(row)
        self.metrics.observe("ttft", time.perf_counter() - job.t_submit)

        slot = _Slot(request_id=job.request_id, future=job.future,
                     pos=len(job.prompt), token=first_token,
                     max_new=job.max_new, eos_id=job.eos_id,
                     pinned=pinned, prompt=job.prompt)
        slot.generated.append(slot.token)
        pool.slots[job.slot_idx] = slot
        self._maybe_retire(pool, job.slot_idx)

    def _finish_prefill(self, pool: _BucketPool, row: int,
                        first_token: int) -> None:
        """One job's last chunk ran: commit its aligned chunks into the
        trie, migrate the staging row into the reserved pool slot, free
        the row, and open the decode slot."""
        import jax.numpy as jnp

        job = pool.jobs.pop(row)
        pinned = list(job.prefix_nodes)
        if pool.trie is not None:
            nodes = list(job.prefix_nodes)
            for j in range(len(nodes), len(job.prompt) // pool.chunk):
                chunk_toks = job.prompt[j * pool.chunk:(j + 1) * pool.chunk]
                node = pool.trie.lookup_node(nodes, chunk_toks)
                if node is None:
                    kv = self._extract_for(pool.chunk)(
                        pool.staging, jnp.asarray(row, jnp.int32),
                        jnp.asarray(j * pool.chunk, jnp.int32))
                    node = pool.trie.commit(nodes, chunk_toks, kv)
                if node is None:
                    break  # byte budget exhausted; partial path is fine
                nodes.append(node)
            # hold the full committed path for the slot's lifetime
            pool.trie.unpin(job.prefix_nodes)
            pool.trie.pin(nodes)
            pinned = nodes
            self._audit_prefix_cache(pool)
        pool.cache = self._migrate_c(pool.cache, pool.staging,
                                     jnp.asarray(row, jnp.int32),
                                     jnp.asarray(job.slot_idx, jnp.int32))
        pool.free_rows.append(row)
        self.metrics.observe("ttft", time.perf_counter() - job.t_submit)

        slot = _Slot(request_id=job.request_id, future=job.future,
                     pos=len(job.prompt), token=first_token,
                     max_new=job.max_new, eos_id=job.eos_id,
                     pinned=pinned, prompt=job.prompt)
        slot.generated.append(slot.token)
        pool.slots[job.slot_idx] = slot
        self._maybe_retire(pool, job.slot_idx)

    # ------------------------------------------------------------- decoding
    def _retire(self, pool, slot_idx: int, reason: str) -> None:
        slot = pool.slots.pop(slot_idx)
        pool.free.append(slot_idx)
        if self._drafter is not None:
            self._drafter.forget(slot.request_id)
            self._spec_ewma.pop(slot.request_id, None)
            self._spec_idle.pop(slot.request_id, None)
        if self._paged:
            for pid in pool.table.unmap_row(slot_idx):
                pool.pool.release(pid)
        if pool.trie is not None and slot.pinned:
            pool.trie.unpin(slot.pinned)
        if self._paged:
            self._audit_kv(pool, f"retire[{reason}]")
        slot.future.set_result({"ids": list(slot.generated),
                                "finish_reason": reason})
        self.metrics.inc("requests_completed")

    def _maybe_retire(self, pool: _BucketPool, slot_idx: int) -> bool:
        slot = pool.slots[slot_idx]
        if slot.eos_id is not None and slot.token == slot.eos_id:
            self._retire(pool, slot_idx, "eos")
        elif len(slot.generated) >= slot.max_new:
            self._retire(pool, slot_idx, "length")
        elif slot.pos >= pool.bucket:
            self._retire(pool, slot_idx, "bucket_full")
        else:
            return False
        return True

    def _decode_round(self, pool, only: Optional[set] = None) -> None:
        """One compiled decode step over ALL slots of `pool` (fixed
        shapes: the signature cache stays at one entry per bucket — and
        at ONE entry total for the paged layout, whose only per-step
        variation is page-table DATA).

        `only` restricts the round to the given slot indices — PAGED
        layout only (excluded rows keep a sentinel table row so their
        dead-row write drops; the bucketed cache has no sentinel, so an
        excluded bucketed slot would take a garbage write at row 0).
        The speculative scheduler uses it to plain-decode the slots a
        verify round could not carry."""
        import jax
        import jax.numpy as jnp

        live = [i for i in pool.slots if only is None or i in only]
        token = np.zeros((pool.n_slots,), np.int32)
        pos = np.zeros((pool.n_slots,), np.int32)
        for idx in live:
            token[idx] = pool.slots[idx].token
            pos[idx] = pool.slots[idx].pos
        if self._paged:
            # only actively-decoding rows expose their table row: a
            # reserved-but-still-prefilling slot's pages (possibly
            # SHARED prefix pages) must not take the dead-row write this
            # step lands at pos 0 — sentinel rows drop it instead
            tbl = np.full((pool.n_slots, pool.max_pages),
                          pool.pool.sentinel, np.int32)
            for idx in live:
                tbl[idx] = pool.table.array[idx]
            args = (pool.arena, self.params, jnp.asarray(tbl),
                    jnp.asarray(token), jnp.asarray(pos))
            compiled = self._paged_c("decode")
        else:
            args = (pool.cache, self.params, jnp.asarray(token),
                    jnp.asarray(pos))
            compiled = self._decode_c
        result = compiled.get_compiled(*args)
        if pool.bucket not in self._audited:
            self._audited.add(pool.bucket)
            self._audit_donation(result, pool.bucket)
            self._audit_host_aliases(pool)
            if self._paged:
                self._audit_kv(pool, "first_decode")
                if "k_scale" in pool.arena:
                    self._audit_quant_program(result, "first_decode")
        t0 = time.perf_counter()
        if self._paged:
            pool.arena, nxt = result.tree_jitted(*args)
        else:
            pool.cache, nxt = result.tree_jitted(*args)
        nxt = np.asarray(jax.block_until_ready(nxt))
        dt = time.perf_counter() - t0
        for idx in live:
            slot = pool.slots[idx]
            slot.token = int(nxt[idx])
            slot.pos += 1
            slot.generated.append(slot.token)
            self._maybe_retire(pool, idx)
        self.metrics.record_decode_step(len(live), pool.n_slots, dt)
        if self._paged:
            in_use, held = pool.occupancy()
            self.metrics.record_kv_pool(
                in_use, held, pool.chunk,
                quant_bytes_saved=(pool.model_page_bytes
                                   - pool.page_bytes) * in_use)

    # ------------------------------------------------ speculative decoding
    def _spec_round(self, pool) -> bool:
        """One speculative draft/verify round over `pool`
        (serve/speculate.py describes the accept rule).  Returns False
        when no slot can ride a verify step this round — the caller
        falls back to a plain decode round, so speculation never stalls
        decode.

        Bucketed pools are all-or-nothing: the verify program writes
        k+1 cache rows for EVERY row, so every live slot needs headroom
        (pos + k + 1 <= bucket) — near the wall the pool rides plain
        decode for its last few tokens.  Slots without a draft ride
        anyway with pad drafts (position 0 of the verify output is the
        plain-greedy token, so they commit at least one token, exactly
        like a decode step).

        Paged pools are per-slot: sentinel table rows drop excluded
        rows' writes, so eligible slots (draft + headroom + speculative
        spill windows mappable) verify while the rest take a plain
        decode call (`_decode_round(only=...)`)."""
        k = self._spec_k
        if self._spec_gate_idle > 0:
            # pacing after a round that closed below the full-batch
            # break-even (_commit_verify) — plain decode rounds until
            # the next attempt, which doubles as the refresh probe
            self._spec_gate_idle -= 1
            return False
        drafts: Dict[int, List[int]] = {}
        for idx, slot in pool.slots.items():
            rid = slot.request_id
            ewma = self._spec_ewma.get(rid)
            if ewma is not None and ewma < _SPEC_EWMA_FLOOR:
                # throttled: recent acceptance below break-even; only
                # probe once per interval to re-detect predictability
                idle = self._spec_idle.get(rid, 0) + 1
                if idle < _SPEC_PROBE_EVERY:
                    self._spec_idle[rid] = idle
                    continue
            self._spec_idle[rid] = 0
            d = self._drafter.propose(
                slot.request_id, slot.prompt + slot.generated, k)
            if d:
                drafts[idx] = (list(int(t) for t in d) + [0] * k)[:k]
        if not drafts:
            return False
        if self._paged:
            return self._verify_round_paged(pool, drafts)
        if any(s.pos + k + 1 > pool.bucket for s in pool.slots.values()):
            return False
        return self._verify_round_bucketed(pool, drafts)

    def _verify_round_bucketed(self, pool: _BucketPool, drafts) -> bool:
        import jax
        import jax.numpy as jnp

        k = self._spec_k
        tokens = np.zeros((pool.n_slots, k + 1), np.int32)
        pos = np.zeros((pool.n_slots,), np.int32)
        for idx, slot in pool.slots.items():
            tokens[idx, 0] = slot.token
            tokens[idx, 1:] = drafts.get(idx, [0] * k)
            pos[idx] = slot.pos
        args = (pool.cache, self.params, jnp.asarray(tokens),
                jnp.asarray(pos))
        result = self._verify_c().get_compiled(*args)
        if ("bucketed", pool.bucket) not in self._audited_verify:
            self._audited_verify.add(("bucketed", pool.bucket))
            self._audit_verify(result, f"verify[bucket={pool.bucket}]")
        t0 = time.perf_counter()
        pool.cache, nxt = result.tree_jitted(*args)
        nxt = np.asarray(jax.block_until_ready(nxt))
        dt = time.perf_counter() - t0
        # rejected rows need no explicit cleanup in the bucketed layout:
        # the pos cursor simply does not advance past the accepted
        # prefix, the next write at pos overwrites the stale row, and
        # the length mask hides everything past the query position
        proposed, accepted, committed = self._commit_verify(
            pool, drafts, tokens, nxt, list(pool.slots))
        self.metrics.record_speculation(
            proposed, accepted, committed, len(drafts), pool.n_slots, dt)
        return True

    def _verify_round_paged(self, pool: _PagedPool, drafts) -> bool:
        import jax
        import jax.numpy as jnp

        k = self._spec_k
        eligible: Dict[int, List[int]] = {}
        for idx, d in drafts.items():
            slot = pool.slots[idx]
            if slot.pos + k + 1 > pool.bucket:
                continue
            # speculative rows may spill past the slot's up-front page
            # reservation; map the spill windows now (the rollback below
            # unconditionally truncates the row back to the reservation,
            # so outside a verify round the invariant "live slots map
            # exactly their reservation" always holds)
            n_need = (slot.pos + k) // pool.chunk + 1
            n_have = pool.table.n_mapped(idx)
            if n_need > n_have:
                if not pool.make_room(n_need - n_have):
                    continue
                for j in range(n_have, n_need):
                    pool.table.map(idx, j, pool.pool.alloc())
            eligible[idx] = d
        if not eligible:
            return False
        tokens = np.zeros((pool.n_slots, k + 1), np.int32)
        pos = np.zeros((pool.n_slots,), np.int32)
        tbl = np.full((pool.n_slots, pool.max_pages),
                      pool.pool.sentinel, np.int32)
        for idx, d in eligible.items():
            slot = pool.slots[idx]
            tokens[idx, 0] = slot.token
            tokens[idx, 1:] = d
            pos[idx] = slot.pos
            tbl[idx] = pool.table.array[idx]
        args = (pool.arena, self.params, jnp.asarray(tbl),
                jnp.asarray(tokens), jnp.asarray(pos))
        result = self._paged_c("verify").get_compiled(*args)
        if ("paged", pool.bucket) not in self._audited_verify:
            self._audited_verify.add(("paged", pool.bucket))
            self._audit_verify(result, f"verify[paged cap={pool.bucket}]")
        t0 = time.perf_counter()
        pool.arena, nxt = result.tree_jitted(*args)
        nxt = np.asarray(jax.block_until_ready(nxt))
        dt = time.perf_counter() - t0
        # reservation sizes BEFORE the commit walk can retire the slots
        reserved = {idx: pool.pages_needed(len(pool.slots[idx].prompt),
                                           pool.slots[idx].max_new)
                    for idx in eligible}
        rest = [i for i in pool.slots if i not in eligible]
        proposed, accepted, committed = self._commit_verify(
            pool, drafts, tokens, nxt, list(eligible))
        # rollback: spill windows past the reservation only ever hold
        # rejected/uncommitted draft rows (committed positions provably
        # fit the reservation — pages_needed covers prompt + max_new),
        # so truncating the table tail releases them.  Retired slots
        # were already fully unmapped by _retire.
        released = 0
        for idx in eligible:
            if idx not in pool.slots:
                continue
            for pid in pool.table.unmap_tail(idx, reserved[idx]):
                pool.pool.release(pid)
                released += 1
        if released:
            self._audit_spec_rollback(pool)
        self.metrics.record_speculation(
            proposed, accepted, committed, len(eligible), pool.n_slots,
            dt, pages_released=released)
        in_use, held = pool.occupancy()
        self.metrics.record_kv_pool(
            in_use, held, pool.chunk,
            quant_bytes_saved=(pool.model_page_bytes
                               - pool.page_bytes) * in_use)
        if rest:
            self._decode_round(pool, only=set(rest))
        return True

    def _commit_verify(self, pool, drafts, tokens, nxt, idxs):
        """Commit walk for the slots that rode a verify step: accept the
        longest draft prefix the target's own greedy picks ratify, plus
        the target's correction/bonus token.  Every committed token is
        the exact plain-greedy token (the draft row only decides how
        many commit per round), so retire semantics (eos/length/
        bucket_full) are checked token-by-token exactly as a sequence
        of plain decode rounds would.  Returns (proposed, accepted,
        committed) counts for the speculation metrics."""
        k = self._spec_k
        proposed = accepted = committed = 0
        expect = 0.0
        for idx in idxs:
            d_row = tokens[idx, 1:]
            g_row = nxt[idx]
            # the accept rule is self-validating, so pad drafts on
            # draftless rows are safe — an accidental pad match is a
            # genuine accept; only REAL proposals count toward the rate
            n_acc = accept_length(d_row, g_row[:k])
            self._audit_spec_bookkeeping(d_row, g_row, n_acc,
                                         f"slot={idx}")
            if idx in drafts:
                proposed += k
                accepted += n_acc
                rid = pool.slots[idx].request_id
                prev = self._spec_ewma.get(rid, float(n_acc))
                self._spec_ewma[rid] = ((1 - _SPEC_EWMA_ALPHA) * prev
                                        + _SPEC_EWMA_ALPHA * n_acc)
                expect += self._spec_ewma[rid]
            for i in range(n_acc + 1):
                slot = pool.slots[idx]
                slot.token = int(g_row[i])
                slot.pos += 1
                slot.generated.append(slot.token)
                committed += 1
                if self._maybe_retire(pool, idx):
                    break
        # full-batch economics (see _SPEC_VERIFY_COST): expected accepts
        # from the drafting rows' refreshed EWMAs must cover the pad
        # rows' share of the k+1-wide program, else pace speculation
        if drafts and expect < (_SPEC_VERIFY_COST - 1.0) * max(
                1, len(idxs)):
            self._spec_gate_idle = _SPEC_PROBE_EVERY - 1
        return proposed, accepted, committed

    def _audit_verify(self, result, node: str) -> None:
        """SERVE003 (program arm): the verify step must donate its cache
        and length-mask attention past the committed positions —
        audited once per compiled verify signature."""
        try:
            from easydist_tpu.analyze import check_speculative_rewind

            check_speculative_rewind(result=result, node=node)
        except ImportError:  # analyze is an optional layer at runtime
            pass

    def _audit_spec_bookkeeping(self, draft, target, n_accepted: int,
                                node: str) -> None:
        """SERVE003 (bookkeeping arm): the accepted prefix must never
        advance past the first draft/target mismatch."""
        try:
            from easydist_tpu.analyze import check_speculative_rewind

            check_speculative_rewind(
                draft=[int(t) for t in draft],
                target=[int(t) for t in target],
                n_accepted=n_accepted, node=f"verify[{node}]")
        except ImportError:
            pass

    def _audit_spec_rollback(self, pool: _PagedPool) -> None:
        """SERVE003 (paged arm): after a rollback released spill pages,
        no table row may still point at a released page."""
        try:
            from easydist_tpu.analyze import check_speculative_rewind

            check_speculative_rewind(pool=pool.pool, table=pool.table,
                                     trie=pool.trie,
                                     node="verify[rollback]")
        except ImportError:
            pass

    def _audit_donation(self, result, bucket: int) -> None:
        try:
            from easydist_tpu.analyze import check_decode_donation

            check_decode_donation(result, node=f"decode[bucket={bucket}]")
        except ImportError:  # analyze is an optional layer at runtime
            pass

    def _audit_chunked_prefill(self, result, bucket: int) -> None:
        try:
            from easydist_tpu.analyze import check_chunked_prefill

            check_chunked_prefill(result,
                                  node=f"prefill_chunk[bucket={bucket}]")
        except ImportError:
            pass

    def _audit_prefix_cache(self, pool) -> None:
        try:
            from easydist_tpu.analyze import check_prefix_cache

            check_prefix_cache(pool.trie,
                               node=f"prefix_cache[bucket={pool.bucket}]")
        except ImportError:
            pass

    def _audit_host_aliases(self, pool) -> None:
        """ALIAS004: the buffers the next dispatch donates (cache +
        staging, or the paged arena) must not be reachable from
        host-held references that outlive the step — trie nodes must
        hold `_extract` COPIES (bucketed) or page references (paged,
        `kv.is_page_ref`), never the donated arrays themselves."""
        try:
            from easydist_tpu.analyze import check_host_aliases
        except ImportError:  # analyze is an optional layer at runtime
            return
        if self._paged:
            donated = {"arena": pool.arena}
        else:
            donated = {"cache": pool.cache, "staging": pool.staging}
        holders = {}
        if pool.trie is not None:
            holders["trie"] = [node.kv for node in pool.trie._walk()]
        check_host_aliases(donated, holders,
                           node=f"session[bucket={pool.bucket}]")

    def _audit_kv(self, pool: _PagedPool, where: str) -> None:
        """KV001: page-table/refcount audit at the state transitions
        where drift would matter (first decode, every retire).  Layer 13
        rides along: KVQ001 (scale/payload desync) when the arena is
        quantized, KVQ003 (manifest round trip) when a tier is up."""
        try:
            from easydist_tpu.analyze import (check_page_table,
                                              check_quant_arena,
                                              check_tier_roundtrip)

            check_page_table(pool.pool, pool.table, trie=pool.trie,
                             node=f"kv[{where}]")
            if "k_scale" in pool.arena:
                check_quant_arena(pool.arena, node=f"kv.quant[{where}]")
            if pool.tier is not None:
                check_tier_roundtrip(pool.tier, node=f"kv.tier[{where}]")
        except ImportError:  # analyze is an optional layer at runtime
            pass

    def _audit_quant_program(self, result, where: str) -> None:
        """KVQ002: the compiled quant step must never feed int8 K/V into
        a dot_general undequantized — run once per program, where the
        donation audit already runs."""
        try:
            from easydist_tpu.analyze import check_quant_program

            check_quant_program(result, node=f"decode.quant[{where}]")
        except ImportError:
            pass

    # ------------------------------------------------------------- driving
    def step(self) -> int:
        """One serving round: admit pending prompts into free slots/rows,
        run at most `prefill_chunks_per_step` prefill chunk calls, then
        one decode step per bucket with live slots, harvesting
        retirements.  Returns the number of tokens generated this round
        (decode tokens; prefill first-tokens count via `prefills`)."""
        # the replica-death fault point sits at the step boundary: tokens
        # from completed steps were already streamed/synced, this step's
        # are lost — exactly the state a real mid-decode crash leaves
        faultinject.crash_point("fleet.replica.crash")
        while self._admit_one():
            pass
        if self._chunked or self._paged:
            budget = self.config.prefill_chunks_per_step
            for pool in self._pools.values():
                if budget <= 0:
                    break
                if pool.jobs:
                    budget -= self._prefill_round(pool, budget)
        before = self.metrics.counter("tokens_generated")
        for pool in self._pools.values():
            if pool.slots:
                if self._drafter is not None and self._spec_round(pool):
                    continue
                self._decode_round(pool)
        self.metrics.set_gauge("queue_depth", self.queue_depth)
        return self.metrics.counter("tokens_generated") - before

    def run_until_drained(self, max_steps: int = 100000) -> None:
        """Drive `step()` until no request is live or queued."""
        for _ in range(max_steps):
            if not self._pending and not any(
                    p.slots or p.jobs for p in self._pools.values()):
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # ------------------------------------------------------------ lifecycle
    def drain(self, wait: bool = True, max_steps: int = 100000):
        """Stop admitting (submits raise `ReplicaDrainingError`), let
        in-flight work retire, and export the tries' hot pages for
        re-admission elsewhere.  `wait=False` only flips the flag — the
        caller keeps driving `step()` (a fleet router does this so its
        OTHER replicas never stall behind this one's drain) and calls
        `export_hot_pages()` itself once `is_drained`.  Returns the hot
        pages (wait=True) or None (wait=False).  Idempotent."""
        self._draining = True
        if not wait:
            return None
        self.run_until_drained(max_steps=max_steps)
        return self.export_hot_pages()

    @property
    def is_draining(self) -> bool:
        return self._draining

    @property
    def is_drained(self) -> bool:
        """No queued, prefilling, or decoding work left."""
        return not self._pending and not any(
            p.slots or p.jobs for p in self._pools.values())

    def export_hot_pages(self) -> Dict[int, List[List[tuple]]]:
        """Per-bucket root-to-leaf chunk paths from each trie,
        hottest-first (prefix_cache.hot_paths) — what a router re-imports
        into surviving replicas on drain so shared-prefix traffic does
        not re-pay prefill after a scale-down."""
        return {b: ([self._materialize_path(p, path)
                     for path in p.trie.hot_paths()]
                    if self._paged else p.trie.hot_paths())
                for b, p in self._pools.items() if p.trie is not None}

    # ------------------------------------------------- fleet trie access
    def _trie_bucket(self, bucket: Optional[int]) -> Optional[int]:
        """The pool key `bucket` maps to: itself, or the single paged
        pool's capacity cap."""
        if bucket is None:
            return None
        return max(self.config.decode_buckets) if self._paged else bucket

    def _materialize_path(self, pool, path: List[tuple]) -> List[tuple]:
        """Fleet transport of paged trie entries: replace {"page": id}
        references with the page's actual K/V (the same
        [layers, heads, chunk, head_dim] arrays a bucketed trie commits),
        so exported paths are layout-agnostic on the wire."""
        import jax.numpy as jnp

        out = []
        for key, kv in path:
            if is_page_ref(kv):
                kv = self._paged_c("export")(
                    pool.arena, jnp.asarray(int(kv["page"]), jnp.int32))
            elif is_host_ref(kv):
                # demoted chunk: serve the manifest-verified host copy
                # (tier entry stays — this is an export, not a promotion)
                try:
                    host_kv = pool.tier.get(kv["host"]) \
                        if pool.tier is not None else None
                except (KeyError, TierError):
                    host_kv = None
                if host_kv is None:
                    break  # keep the exportable prefix contiguous
                kv = {k: jnp.asarray(v) for k, v in host_kv.items()}
            out.append((key, kv))
        return out

    def _import_path_paged(self, pool, path: Sequence[tuple]) -> int:
        """Commit a transported (materialized) chunk path into the paged
        trie: each chunk lands in a freshly allocated arena page, written
        by the compiled import program and committed as a page
        reference.  First-commit-wins like `PrefixCache.import_path`;
        stops when the arena or the trie budget refuses a page."""
        import jax.numpy as jnp

        nodes: List[object] = []
        for key, kv in path:
            node = pool.trie.lookup_node(nodes, key)
            if node is None:
                if set(kv) != set(pool.arena):
                    # precision/layout mismatch (e.g. a quantized page
                    # offered to an exact arena): recompute locally
                    # rather than coerce payload without its scales
                    break
                if not pool.make_room(1):
                    break
                pid = pool.pool.alloc()
                pool.arena = self._paged_c("import")(
                    pool.arena, kv, jnp.asarray(pid, jnp.int32))
                node = pool.trie.commit(nodes, key, {"page": pid},
                                       nbytes=pool.page_bytes)
                if node is None:
                    pool.pool.release(pid)
                    break
            nodes.append(node)
        return len(nodes)

    def bucket_chunk(self, prompt: Sequence[int]) -> Optional[int]:
        """Trie page size (tokens) for the bucket `prompt` decodes in, or
        None when the prompt fits no bucket / prefix reuse is off."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        if bucket is None or not (self._chunked or self._paged) \
                or not self.config.enable_prefix_cache \
                or not self.config.prefix_cache_bytes:
            return None
        return min(self.config.prefill_chunk, self._trie_bucket(bucket))

    def prefix_affinity(self, prompt: Sequence[int]) -> int:
        """Tokens of `prompt` already committed in this session's trie —
        non-mutating (PrefixCache.peek), so a router can probe every
        replica without disturbing LRU state."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pools.get(self._trie_bucket(bucket)) \
            if bucket is not None else None
        if pool is None or pool.trie is None:
            return 0
        return pool.trie.peek(prompt, max_tokens=len(prompt) - 1)

    def export_prefix_path(self, prompt: Sequence[int],
                           max_tokens: Optional[int] = None) -> List[tuple]:
        """Committed chunk path for `prompt`'s longest cached prefix, as
        [(chunk_tokens, kv)] for transport to another replica (paged
        sessions materialize their page references into real arrays)."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pools.get(self._trie_bucket(bucket)) \
            if bucket is not None else None
        if pool is None or pool.trie is None:
            return []
        path = pool.trie.export_path(prompt, max_tokens=max_tokens)
        return self._materialize_path(pool, path) if self._paged else path

    def import_prefix_path(self, prompt: Sequence[int],
                           path: Sequence[tuple]) -> int:
        """Commit a transported chunk path into the trie of the bucket
        `prompt` will decode in (creating the pool if needed).  Returns
        chunks present along the path afterwards."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        if bucket is None:
            return 0
        pool = self._pool_for(bucket)
        if pool.trie is None:
            return 0
        if self._paged:
            return self._import_path_paged(pool, path)
        return pool.trie.import_path(path)

    def import_hot_pages(self, pages: Dict[int, List[List[tuple]]]) -> int:
        """Re-admit another replica's exported hot pages (drain
        migration): each bucket's paths import into this session's same
        bucket when configured here, falling back to the largest
        configured bucket.  Returns total chunks committed."""
        total = 0
        for bucket, paths in pages.items():
            b = bucket if bucket in self.config.decode_buckets \
                else max(self.config.decode_buckets)
            pool = self._pool_for(b)
            if pool.trie is None:
                continue
            for path in paths:
                total += (self._import_path_paged(pool, path)
                          if self._paged else pool.trie.import_path(path))
        return total

    def snapshot_inflight(self) -> List[Dict[str, object]]:
        """Progress of every live request, keyed by its future (identity
        — the only handle a router shares with this session).  `ids` is
        the tokens already emitted, i.e. what a streaming client has
        already received; a router syncs these into per-request
        `ResumeDescriptor`s after each step so a crash of THIS session
        can be recovered bitwise by resubmitting prompt+ids elsewhere.
        Read-only: no session state changes."""
        out: List[Dict[str, object]] = []
        for prompt, max_new, eos, fut, _t in self._pending:
            out.append({"future": fut, "prompt": list(prompt), "ids": [],
                        "max_new": max_new, "eos_id": eos,
                        "stage": "queued"})
        for pool in self._pools.values():
            for job in pool.jobs.values():
                out.append({"future": job.future,
                            "prompt": list(job.prompt), "ids": [],
                            "max_new": job.max_new, "eos_id": job.eos_id,
                            "stage": "prefill"})
            for slot in pool.slots.values():
                out.append({"future": slot.future,
                            "prompt": list(slot.prompt),
                            "ids": list(slot.generated),
                            "max_new": slot.max_new,
                            "eos_id": slot.eos_id, "stage": "decode"})
        return out

    def evacuate(self) -> List[Dict[str, object]]:
        """Preemptive drain (SIGTERM grace too short to retire decodes):
        retire EVERY live request immediately with finish_reason
        "evacuated" and partial ids, returning resume descriptors.  A
        router resubmits prompt + ids with the remaining budget elsewhere;
        greedy continuation is a pure function of the token prefix, so the
        concatenated output is bitwise-identical to an uninterrupted run.
        An evacuated partial never contains eos (eos retires the slot the
        step it appears) and is always shorter than max_new (reaching it
        retires as "length"), so the remaining budget is >= 1."""
        self._draining = True
        out: List[Dict[str, object]] = []
        while self._pending:
            prompt, max_new, eos, fut, _ = self._pending.popleft()
            if fut.set_running_or_notify_cancel() is False:
                continue
            fut.set_result({"ids": [], "finish_reason": "evacuated"})
            out.append({"prompt": list(prompt), "ids": [],
                        "max_new": max_new, "eos_id": eos})
        for pool in self._pools.values():
            for row in list(pool.jobs):
                job = pool.jobs.pop(row)
                pool.free_rows.append(row)
                pool.free.append(job.slot_idx)
                if self._paged:
                    for pid in pool.table.unmap_row(job.slot_idx):
                        pool.pool.release(pid)
                if pool.trie is not None:
                    pool.trie.unpin(job.prefix_nodes)
                job.future.set_result(
                    {"ids": [], "finish_reason": "evacuated"})
                out.append({"prompt": list(job.prompt), "ids": [],
                            "max_new": job.max_new, "eos_id": job.eos_id})
            for idx in list(pool.slots):
                slot = pool.slots[idx]
                desc = {"prompt": list(slot.prompt),
                        "ids": list(slot.generated),
                        "max_new": slot.max_new, "eos_id": slot.eos_id}
                self._retire(pool, idx, "evacuated")
                out.append(desc)
        return out

    def close(self) -> None:
        """Drain, then release the pooled device caches.  Idempotent;
        every submit afterwards raises `ReplicaDrainingError`."""
        if self._closed:
            return
        self.drain(wait=True)
        self._closed = True
        self._pools.clear()

    # ----------------------------------------------------------- reporting
    def stats(self) -> Dict[str, object]:
        return {
            "replica_id": self.replica_id,
            "draining": self._draining,
            "queue_depth": self.queue_depth,
            "pending": len(self._pending),
            "buckets": {
                b: {"active": p.n_active, "free": len(p.free),
                    "prefilling": len(p.jobs),
                    "free_rows": len(p.free_rows),
                    "prefix_cache": (p.trie.stats() if p.trie else None),
                    **({"kv_pool": p.pool.stats(),
                        "kv_table_mapped": int(
                            (p.table.array != p.table.sentinel).sum())}
                       if self._paged else {})}
                for b, p in self._pools.items()},
            "decode_signatures": (
                self._paged_cs["decode"].cache_stats()
                if self._paged and "decode" in self._paged_cs
                else self._decode_c.cache_stats()),
            "prefill_signatures": (
                self._paged_cs["chunk"].cache_stats()
                if self._paged and "chunk" in self._paged_cs
                else (self._prefill_chunk_c if self._chunked
                      else self._prefill_c).cache_stats()),
            "verify_signatures": (
                self._paged_cs["verify"].cache_stats()
                if self._paged and "verify" in self._paged_cs
                else (self._verify_cs["verify"].cache_stats()
                      if "verify" in self._verify_cs else None)),
            "migrate_signatures": self._migrate_c.cache_stats(),
            "metrics": self.metrics.snapshot(),
        }

    # --------------------------------------------------------- constructors
    @classmethod
    def _wire_draft_model(cls, kw, draft_model, decode_step, init_cache,
                          seq_bound: Optional[int]) -> None:
        """Turn a `draft_model=(params, cfg)` pair into a
        `SmallModelDrafter` over the family's own decode step (in `kw`
        as `drafter`, unless the caller passed one explicitly)."""
        if draft_model is None or kw.get("drafter") is not None:
            return
        from .speculate import SmallModelDrafter

        dparams, dcfg = draft_model
        scfg = kw.get("config") or ServeConfig()
        max_len = max(scfg.decode_buckets)
        if seq_bound is not None:
            max_len = min(max_len, seq_bound)
        kw["drafter"] = SmallModelDrafter(
            dparams,
            model_decode=lambda p, c, t, pos: decode_step(
                p, dcfg, c, t, pos),
            init_cache=lambda b, L: init_cache(dcfg, b, L),
            max_len=max_len, mesh=kw.get("mesh"))

    @classmethod
    def for_gpt(cls, params, cfg, *, draft_model=None, **kw):
        """Session over models/gpt.py; decode_buckets must fit cfg.seq
        (the learned-position-table bound).  `draft_model=(params, cfg)`
        wires a `SmallModelDrafter` over a second (smaller) gpt for
        `speculate_drafter="draft_model"`."""
        import dataclasses

        from easydist_tpu.models import gpt

        kw.setdefault("compile_key", ("gpt", dataclasses.astuple(cfg)))
        if draft_model is not None:
            cls._wire_draft_model(kw, draft_model, gpt.gpt_decode_step,
                                  gpt.init_kv_cache,
                                  seq_bound=draft_model[1].seq)
        return cls(
            params,
            model_prefill=lambda p, c, t, l: gpt.gpt_prefill(p, cfg, c, t, l),
            model_prefill_chunk=lambda p, c, t, s, l: gpt.gpt_prefill_chunk(
                p, cfg, c, t, s, l),
            model_decode=lambda p, c, t, pos: gpt.gpt_decode_step(
                p, cfg, c, t, pos),
            init_cache=lambda b, L, dt=None: gpt.init_kv_cache(
                cfg, b, L, dtype=dt),
            model_prefill_chunk_paged=lambda p, pg, tb, t, s, l:
                gpt.gpt_prefill_chunk_paged(p, cfg, pg, tb, t, s, l),
            model_decode_paged=lambda p, pg, tb, t, pos:
                gpt.gpt_decode_step_paged(p, cfg, pg, tb, t, pos),
            init_pages=lambda n, t, dt=None, **qkw: gpt.init_kv_pages(
                cfg, n, t, dtype=dt, **qkw),
            model_verify=lambda p, c, t, pos: gpt.gpt_verify_step(
                p, cfg, c, t, pos),
            model_verify_paged=lambda p, pg, tb, t, pos:
                gpt.gpt_verify_step_paged(p, cfg, pg, tb, t, pos),
            max_prompt_len=cfg.seq, **kw)

    @classmethod
    def for_llama(cls, params, cfg, *, draft_model=None, **kw):
        """Session over models/llama.py (RoPE: buckets are not bound by
        cfg.seq).  `draft_model=(params, cfg)` wires a
        `SmallModelDrafter` over a second (smaller) llama for
        `speculate_drafter="draft_model"`."""
        import dataclasses

        from easydist_tpu.models import llama

        kw.setdefault("compile_key", ("llama", dataclasses.astuple(cfg)))
        if draft_model is not None:
            cls._wire_draft_model(kw, draft_model,
                                  llama.llama_decode_step,
                                  llama.init_kv_cache, seq_bound=None)
        return cls(
            params,
            model_prefill=lambda p, c, t, l: llama.llama_prefill(
                p, cfg, c, t, l),
            model_prefill_chunk=lambda p, c, t, s, l:
                llama.llama_prefill_chunk(p, cfg, c, t, s, l),
            model_decode=lambda p, c, t, pos: llama.llama_decode_step(
                p, cfg, c, t, pos),
            init_cache=lambda b, L, dt=None: llama.init_kv_cache(
                cfg, b, L, dtype=dt),
            model_prefill_chunk_paged=lambda p, pg, tb, t, s, l:
                llama.llama_prefill_chunk_paged(p, cfg, pg, tb, t, s, l),
            model_decode_paged=lambda p, pg, tb, t, pos:
                llama.llama_decode_step_paged(p, cfg, pg, tb, t, pos),
            init_pages=lambda n, t, dt=None, **qkw: llama.init_kv_pages(
                cfg, n, t, dtype=dt, **qkw),
            model_verify=lambda p, c, t, pos: llama.llama_verify_step(
                p, cfg, c, t, pos),
            model_verify_paged=lambda p, pg, tb, t, pos:
                llama.llama_verify_step_paged(p, cfg, pg, tb, t, pos),
            **kw)
