"""Token-level decode serving: `GenerationSession`.

`ServeEngine` serves request-shaped functions — every call re-runs the
whole forward.  For autoregressive generation that is O(T^2) attention
flops per sequence; the KV cache makes each token O(T).  This module is
the serving half of the cache-carrying model API
(models/gpt.py::gpt_prefill_chunk/gpt_decode_step and the llama mirror):

  * **chunked, batched prefill** — each admitted prompt is processed in
    fixed [prefill_batch, prefill_chunk] windows against a multi-row
    staging cache, so ONE compiled prefill signature per bucket serves
    every prompt length (PR 9 compiled one per pow2-padded length), and
    up to `prefill_batch` pending prompts share each chunk call;
  * **prefix-reuse KV cache** — finished prefills commit their aligned
    KV chunks into a per-bucket token trie (serve/prefix_cache.py);
    admission restores the longest cached whole-chunk prefix with
    `dynamic_update_slice` and resumes prefill at `prefix_len` instead
    of 0.  Restored and recomputed KV are bitwise identical, so the
    cache is a pure latency optimization (`enable_prefix_cache=False`
    produces bitwise-identical outputs);
  * **bounded prefill pressure** — `step()` interleaves at most
    `prefill_chunks_per_step` chunk calls before the decode rounds run,
    so a long prompt cannot stall in-flight decodes for its whole
    prefill (decode p99 stays bounded);
  * **bucketed KV pool + one compiled decode step** — unchanged from
    PR 9: one slot pool per `ServeConfig.decode_buckets` entry, decode
    always steps ALL slots, slots recycle through a free list;
  * **paged KV pool** (`ServeConfig.kv_layout="paged"`) — ALL buckets
    collapse into ONE page-granular pool over a preallocated arena
    (kv/pool.py + kv/table.py): sequences of any length share one
    compiled decode step (the int32 page table, fixed
    [max_slots, max_pages], is the only per-step state that varies), a
    restored prefix is table entries pointing at trie-committed pages
    (zero copies — the bucketed path `dynamic_update_slice`-copies every
    restored chunk), and prefill writes arena pages directly through the
    table (no staging cache, no migrate).  Admission reserves every page
    a sequence can ever touch up front, so the table row is static for
    the slot's life; analyze rule KV001 audits the refcount/table
    bookkeeping at first decode and every retire;
  * **donated caches** — pool and staging are positional arg 0 and
    output 0 of their compiled callables, so `infer_state_io` pairs and
    donates them; XLA updates in place instead of copying.  `analyze`
    rules SERVE001 (decode) and SERVE002 (chunked prefill: donation +
    length-masked attention + trie accounting) audit exactly this.

Sharding rides the existing solver: the cache's heads axis (dim 2) is the
tensor-parallel shard dim, matching the attention strategy the solver
picks for the model itself, so tp serving works unchanged —
`kv_cache_specs` names the placement for callers that want to lay the
pool out explicitly.
"""

from __future__ import annotations

import collections
import logging
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from easydist_tpu.kv import PagePool, PageTable
from easydist_tpu.resilience import faultinject

from .admission import ReplicaDrainingError, RequestTooLargeError
from .batcher import select_bucket
from .engine import ServeConfig
from .metrics import ServeMetrics
from .prefix_cache import PrefixCache

logger = logging.getLogger(__name__)


# process-level memo of compiled step functions, keyed by (model identity,
# mesh).  Every compiled callable below is pure — cache, params, and tokens
# all cross as arguments — so sessions over the same model/mesh can share
# the traced-and-XLA-compiled programs instead of each replica re-paying
# the compile.  This is the fleet case: N in-process replicas differ only
# in the state they carry, never in the program they run.
_COMPILED_MEMO: Dict[tuple, tuple] = {}


def kv_cache_specs(axis: str = "tp"):
    """PartitionSpec pytree for a KV cache {"k", "v"} of shape
    [layers, batch/slots, heads, max_len, head_dim]: heads sharded on
    `axis`, everything else replicated — the placement consistent with a
    tensor-parallel attention strategy."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis, None, None)
    return {"k": spec, "v": spec}


@dataclass
class _Slot:
    """Host-side view of one pooled decode row."""
    request_id: int
    future: Future
    pos: int                      # next cache write position
    token: int                    # last generated token (not yet in cache)
    max_new: int
    eos_id: Optional[int]
    generated: List[int] = field(default_factory=list)
    pinned: List[object] = field(default_factory=list)  # trie nodes held
    prompt: List[int] = field(default_factory=list)  # for evacuation


@dataclass
class _PrefillJob:
    """One prompt mid-prefill: owns a staging row and a reserved pool
    slot; `start` advances one chunk per batched chunk call."""
    request_id: int
    future: Future
    prompt: List[int]
    max_new: int
    eos_id: Optional[int]
    row: int                      # staging row
    slot_idx: int                 # reserved pool slot
    start: int                    # next chunk start (multiple of chunk)
    prefix_nodes: List[object]    # trie nodes restored (pinned)
    t_submit: float


class _BucketPool:
    """One decode bucket: pooled cache + free-list slot allocator +
    multi-row staging cache shared by the chunked-prefill scheduler +
    the bucket's prefix trie."""

    def __init__(self, bucket: int, n_slots: int, init_cache,
                 n_rows: int = 1, chunk: int = 0,
                 prefix_bytes: int = 0):
        self.bucket = bucket
        self.n_slots = n_slots
        self.cache = init_cache(n_slots, bucket)
        self.n_rows = n_rows
        self.staging = init_cache(n_rows, bucket)
        self.chunk = chunk                      # 0 = legacy one-shot path
        self.free: List[int] = list(range(n_slots))
        self.slots: Dict[int, _Slot] = {}          # slot index -> _Slot
        self.free_rows: List[int] = list(range(n_rows))
        self.jobs: Dict[int, _PrefillJob] = {}     # staging row -> job
        self.trie: Optional[PrefixCache] = \
            PrefixCache(chunk, prefix_bytes) if chunk and prefix_bytes \
            else None

    @property
    def n_active(self) -> int:
        return len(self.slots)


class _PagedPool:
    """The paged layout's single pool: one preallocated page arena, a
    refcounted page allocator, and a fixed [n_slots, max_pages] page
    table shared by every request regardless of length (`bucket` is the
    capacity cap — max(decode_buckets) — not a padding granularity).
    Prefill jobs write arena pages directly through the table, so there
    is no staging cache and no migrate; a restored prefix is table
    entries pointing at trie-committed pages (zero-copy)."""

    def __init__(self, bucket: int, n_slots: int, init_pages,
                 n_rows: int, chunk: int, prefix_bytes: int,
                 n_pages: int):
        self.bucket = bucket
        self.n_slots = n_slots
        self.chunk = chunk                       # page_tokens
        self.max_pages = bucket // chunk
        if n_pages < self.max_pages:
            raise ValueError(
                f"kv_arena_pages {n_pages} cannot hold even one "
                f"full-length sequence ({self.max_pages} pages)")
        self.n_rows = n_rows
        self.arena = init_pages(n_pages, chunk)
        self.page_bytes = sum(int(self.arena[k].nbytes) // n_pages
                              for k in ("k", "v"))
        self.pool = PagePool(n_pages, chunk, page_bytes=self.page_bytes)
        self.table = PageTable(n_slots, self.max_pages, n_pages)
        self.free: List[int] = list(range(n_slots))
        self.slots: Dict[int, _Slot] = {}
        self.free_rows: List[int] = list(range(n_rows))
        self.jobs: Dict[int, _PrefillJob] = {}
        self.trie: Optional[PrefixCache] = \
            PrefixCache(chunk, prefix_bytes,
                        on_evict=self._release_evicted) \
            if prefix_bytes else None

    def _release_evicted(self, node) -> None:
        # trie eviction drops the trie's hold on the node's arena page;
        # the page only frees when no live slot still maps it
        self.pool.release(node.kv["page"])

    @property
    def n_active(self) -> int:
        return len(self.slots)

    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        """Worst-case pages one sequence touches: prefill writes
        ceil(prompt/chunk) whole pages, decode writes up to
        `max_new - 1` more positions, everything capped at the bucket
        (retirement fires at pos >= bucket)."""
        cap = min(self.bucket, prompt_len + max_new)
        return -(-cap // self.chunk)

    def make_room(self, n_pages: int) -> bool:
        """Free arena pages until `n_pages` are available, evicting
        unpinned trie nodes LRU-first (an eviction only yields a free
        page when no live slot shares it).  Returns availability."""
        if self.trie is not None:
            while self.pool.n_free < n_pages:
                if not self.trie.evict_lru():
                    break
        return self.pool.n_free >= n_pages

    def occupancy(self):
        """(pages_in_use, real tokens held) for the kv gauges: slots
        hold `pos` cached tokens, jobs `start` (restored + prefilled so
        far), trie-only pages a whole chunk each; reserved-but-unwritten
        pages count capacity only — that gap IS the fragmentation the
        `kv_page_utilization` gauge measures."""
        tokens = sum(min(s.pos, self.bucket) for s in self.slots.values())
        tokens += sum(j.start for j in self.jobs.values())
        if self.trie is not None:
            mapped = set()
            for idx in self.slots:
                mapped.update(self.table.mapped(idx))
            for job in self.jobs.values():
                mapped.update(self.table.mapped(job.slot_idx))
            for node in self.trie._walk():
                if node.kv["page"] not in mapped:
                    mapped.add(node.kv["page"])
                    tokens += self.chunk
        return self.pool.in_use, tokens


class GenerationSession:
    """Continuous-batching token generation over a cache-carrying model.

    model_prefill(params, cache, tokens, lengths) -> (cache, logits)
    model_decode(params, cache, token, pos) -> (cache, logits)
    model_prefill_chunk(params, cache, tokens, start_pos, lengths)
        -> (cache, logits) — fixed-chunk window at absolute positions;
        enables the chunked/batched/prefix-reuse prefill scheduler (the
        `for_gpt`/`for_llama` constructors wire it; without it the
        session falls back to PR 9's one-shot pow2-padded prefill).
    init_cache(batch, max_len, dtype=None) -> cache pytree

    Greedy decoding (argmax inside the compiled step, so only int32 token
    ids cross the host boundary per token).  `submit` returns a Future
    resolving to {"ids": [...generated ids...], "finish_reason":
    "eos"|"length"|"bucket_full"}; drive with `step()` (admit + bounded
    prefill chunks + decode + harvest) or `run_until_drained()`.

    `compile_key` (any hashable; `for_gpt`/`for_llama` derive one from the
    model config) opts the session into the process-level compiled-program
    memo: replicas over the same model and mesh share traced/compiled step
    functions instead of each paying the compile — the callables are pure,
    so only host-side state is per-session.
    """

    def __init__(self, params, *, model_prefill: Callable,
                 model_decode: Callable, init_cache: Callable,
                 model_prefill_chunk: Optional[Callable] = None,
                 model_prefill_chunk_paged: Optional[Callable] = None,
                 model_decode_paged: Optional[Callable] = None,
                 init_pages: Optional[Callable] = None,
                 config: Optional[ServeConfig] = None, mesh=None,
                 eos_id: Optional[int] = None,
                 max_prompt_len: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None,
                 replica_id: Optional[str] = None,
                 compile_key: Optional[object] = None):
        from easydist_tpu.jaxfront import easydist_compile

        self.config = config or ServeConfig()
        self.replica_id = replica_id
        if max_prompt_len is not None:
            bad = [b for b in self.config.decode_buckets
                   if b > max_prompt_len]
            if bad:
                raise ValueError(
                    f"decode_buckets {bad} exceed the model's maximum "
                    f"sequence length {max_prompt_len}; set "
                    f"ServeConfig(decode_buckets=...) within it")
        self.params = params
        self.mesh = mesh
        self.eos_id = eos_id
        self.metrics = metrics or ServeMetrics(replica_id=replica_id)
        self._draining = False
        self._closed = False
        self._init_cache = init_cache
        self._chunked = model_prefill_chunk is not None
        self._paged = self.config.kv_layout == "paged"
        if self._paged and (model_prefill_chunk_paged is None
                            or model_decode_paged is None
                            or init_pages is None):
            raise ValueError(
                "kv_layout='paged' requires model_prefill_chunk_paged, "
                "model_decode_paged, and init_pages (the for_gpt/"
                "for_llama constructors wire all three)")
        self._init_pages = init_pages
        self._pending: collections.deque = collections.deque()
        self._pools: Dict[int, _BucketPool] = {}
        self._next_request_id = 0
        self._audited: set = set()
        self._audited_prefill: set = set()

        def _prefill(cache, params, tokens, lengths):
            import jax.numpy as jnp

            cache, logits = model_prefill(params, cache, tokens, lengths)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _prefill_chunk(staging, params, tokens, start, lengths):
            import jax.numpy as jnp

            staging, logits = model_prefill_chunk(params, staging, tokens,
                                                  start, lengths)
            return staging, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _restore(staging, chunk_kv, row, start):
            import jax

            return {
                k: jax.lax.dynamic_update_slice(
                    staging[k],
                    chunk_kv[k][:, None].astype(staging[k].dtype),
                    (0, row, 0, start, 0))
                for k in ("k", "v")
            }

        def _migrate(pool, staging, row, slot):
            import jax

            out = {}
            for k in ("k", "v"):
                layers, _, heads, max_len, hd = staging[k].shape
                src = jax.lax.dynamic_slice(
                    staging[k], (0, row, 0, 0, 0),
                    (layers, 1, heads, max_len, hd))
                out[k] = jax.lax.dynamic_update_slice(
                    pool[k], src.astype(pool[k].dtype), (0, slot, 0, 0, 0))
            return out

        def _decode(pool, params, token, pos):
            import jax.numpy as jnp

            pool, logits = model_decode(params, pool, token, pos)
            return pool, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # paged-layout programs: arena first for donation pairing, the
        # int32 page table crosses as data every call (fixed shape — the
        # signature stays closed over arbitrary per-row lengths).
        # Compiled lazily via `_paged_c` so bucketed sessions never pay
        # for them; export/import move single pages for fleet handoff.
        def _prefill_chunk_paged(arena, params, table, tokens, start,
                                 lengths):
            import jax.numpy as jnp

            arena, logits = model_prefill_chunk_paged(
                params, arena, table, tokens, start, lengths)
            return arena, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _decode_paged(arena, params, table, token, pos):
            import jax.numpy as jnp

            arena, logits = model_decode_paged(params, arena, table,
                                               token, pos)
            return arena, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _page_export(arena, page):
            import jax

            return {k: jax.lax.dynamic_index_in_dim(
                        arena[k], page, axis=1, keepdims=False)
                    for k in ("k", "v")}

        def _page_import(arena, chunk_kv, page):
            import jax

            return {k: jax.lax.dynamic_update_index_in_dim(
                        arena[k], chunk_kv[k].astype(arena[k].dtype),
                        page, axis=1)
                    for k in ("k", "v")}

        self._paged_defs = (
            {"chunk": _prefill_chunk_paged, "decode": _decode_paged,
             "export": _page_export, "import": _page_import}
            if model_prefill_chunk_paged is not None else {})

        # pool/staging is arg 0 and output 0 of every mutating compiled
        # callable, so state_io="auto" pairs it and XLA gets the buffer
        # donated; _extract's output is chunk-shaped (no pairing, no
        # donation — it must not invalidate the staging it reads)
        # `mesh=None` means "the global mesh at first call", which is
        # sticky process state that can change between sessions — resolve
        # it NOW so every program this session runs (and every session
        # sharing this memo entry) is compiled against the same mesh.
        # Unresolvable (no global installed yet) skips the memo: the
        # session compiles privately under whatever ambient its first
        # call sees, exactly the pre-memo behavior.
        if mesh is None:
            from easydist_tpu.jaxfront.mesh import get_device_mesh

            mesh = get_device_mesh()
            self.mesh = mesh  # _extract_for compiles against it too
        memo_key = (compile_key, mesh) \
            if compile_key is not None and mesh is not None else None
        shared = _COMPILED_MEMO.get(memo_key) if memo_key else None
        if shared is None:
            shared = (easydist_compile(_prefill, mesh=mesh),
                      easydist_compile(_prefill_chunk, mesh=mesh),
                      easydist_compile(_restore, mesh=mesh),
                      easydist_compile(_migrate, mesh=mesh),
                      easydist_compile(_decode, mesh=mesh),
                      {}, {})
            if memo_key:
                while len(_COMPILED_MEMO) >= 32:  # live sessions keep refs
                    _COMPILED_MEMO.pop(next(iter(_COMPILED_MEMO)))
                _COMPILED_MEMO[memo_key] = shared
        (self._prefill_c, self._prefill_chunk_c, self._restore_c,
         self._migrate_c, self._decode_c, self._extract_cs,
         self._paged_cs) = shared

    def _extract_for(self, chunk_len: int) -> Callable:
        """Compiled chunk extractor for one chunk size (the slice size
        must be static, so each chunk length is its own closure — one per
        distinct bucket chunk, compiled once)."""
        fn = self._extract_cs.get(chunk_len)
        if fn is None:
            from easydist_tpu.jaxfront import easydist_compile

            def _extract(staging, row, start):
                import jax

                out = {}
                for k in ("k", "v"):
                    layers, _, heads, _, hd = staging[k].shape
                    out[k] = jax.lax.dynamic_slice(
                        staging[k], (0, row, 0, start, 0),
                        (layers, 1, heads, chunk_len, hd))[:, 0]
                return out

            fn = easydist_compile(_extract, mesh=self.mesh)
            self._extract_cs[chunk_len] = fn
        return fn

    def _paged_c(self, name: str) -> Callable:
        """Compiled paged program ("chunk" / "decode" / "export" /
        "import"), built on first use and shared through the process
        memo exactly like `_extract_for`."""
        fn = self._paged_cs.get(name)
        if fn is None:
            from easydist_tpu.jaxfront import easydist_compile

            fn = easydist_compile(self._paged_defs[name], mesh=self.mesh)
            self._paged_cs[name] = fn
        return fn

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> Future:
        """Queue one prompt; generation interleaves with every other live
        request (continuous batching) as `step()` is driven."""
        if self._draining or self._closed:
            raise ReplicaDrainingError(
                f"session{f' {self.replica_id}' if self.replica_id else ''} "
                f"is {'closed' if self._closed else 'draining'}: in-flight "
                f"work retires but nothing new is admitted")
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if select_bucket(len(prompt) + 1, self.config.decode_buckets) is None:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens does not fit any decode "
                f"bucket {self.config.decode_buckets} with room to "
                f"generate")
        fut = Future()
        self._pending.append(
            (prompt, max_new_tokens,
             self.eos_id if eos_id is None else eos_id, fut,
             time.perf_counter()))
        self.metrics.inc("requests_submitted")
        self.metrics.set_gauge("queue_depth", self.queue_depth)
        return fut

    @property
    def queue_depth(self) -> int:
        """Live requests this session owns: queued + prefilling + decoding
        (the fleet router's occupancy signal)."""
        return len(self._pending) + sum(
            len(p.jobs) + p.n_active for p in self._pools.values())

    # ------------------------------------------------------------- plumbing
    def _pool_for(self, bucket: int):
        cfg = self.config
        if self._paged:
            # every bucket collapses into the one page-granular pool:
            # lengths are a page-table concern, not a compile-signature
            # concern, so there is nothing to bucket by
            bucket = max(cfg.decode_buckets)
        pool = self._pools.get(bucket)
        if pool is None:
            if self._paged:
                chunk = cfg.kv_page_tokens or min(cfg.prefill_chunk,
                                                  bucket)
                max_pages = bucket // chunk
                n_pages = cfg.kv_arena_pages or \
                    (cfg.max_decode_slots + 1) * max_pages
                pool = _PagedPool(
                    bucket, cfg.max_decode_slots, self._pages_factory,
                    n_rows=cfg.prefill_batch, chunk=chunk,
                    prefix_bytes=(cfg.prefix_cache_bytes
                                  if cfg.enable_prefix_cache else 0),
                    n_pages=n_pages)
            elif self._chunked:
                pool = _BucketPool(
                    bucket, cfg.max_decode_slots, self._cache_factory,
                    n_rows=cfg.prefill_batch,
                    chunk=min(cfg.prefill_chunk, bucket),
                    prefix_bytes=(cfg.prefix_cache_bytes
                                  if cfg.enable_prefix_cache else 0))
            else:
                pool = _BucketPool(bucket, cfg.max_decode_slots,
                                   self._cache_factory)
            self._pools[bucket] = pool
        return pool

    def _cache_factory(self, batch: int, max_len: int):
        dtype = self.config.kv_cache_dtype
        return self._init_cache(batch, max_len,
                                None if dtype == "auto" else dtype)

    def _pages_factory(self, n_pages: int, page_tokens: int):
        dtype = self.config.kv_cache_dtype
        return self._init_pages(n_pages, page_tokens,
                                None if dtype == "auto" else dtype)

    def _prefill_pad(self, plen: int, bucket: int) -> int:
        """Legacy one-shot path: smallest power of two >= plen (floor 8),
        capped at the decode bucket."""
        t = 8
        while t < plen:
            t *= 2
        return min(t, bucket)

    def _admit_one(self) -> bool:
        """Pop one pending request toward generation.  Chunked path:
        reserve a pool slot + staging row, restore the longest cached
        prefix, and enqueue a prefill job (chunks run in `step()`).
        Legacy path: one-shot prefill + migrate, as in PR 9.  Returns
        False when nothing is admissible."""
        import jax.numpy as jnp

        if not self._pending:
            return False
        prompt, max_new, eos, fut, t_submit = self._pending[0]
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pool_for(bucket)
        if not pool.free:
            return False
        if (self._chunked or self._paged) and not pool.free_rows:
            return False
        if self._paged:
            return self._admit_one_paged(pool)
        self._pending.popleft()
        if fut.set_running_or_notify_cancel() is False:
            return True  # cancelled while queued; slot stays free
        slot_idx = pool.free.pop()

        if self._chunked:
            row = pool.free_rows.pop()
            prefix_len, nodes = 0, []
            if pool.trie is not None:
                # cap below len(prompt): at least one real token must run
                # through prefill so the finishing chunk produces logits
                prefix_len, nodes = pool.trie.match(
                    prompt, max_tokens=len(prompt) - 1)
                for j, node in enumerate(nodes):
                    pool.staging = self._restore_c(
                        pool.staging, node.kv,
                        jnp.asarray(row, jnp.int32),
                        jnp.asarray(j * pool.chunk, jnp.int32))
                pool.trie.pin(nodes)
            self.metrics.record_admission(len(prompt), prefix_len)
            pool.jobs[row] = _PrefillJob(
                request_id=self._next_request_id, future=fut,
                prompt=prompt, max_new=max_new, eos_id=eos, row=row,
                slot_idx=slot_idx, start=prefix_len,
                prefix_nodes=nodes, t_submit=t_submit)
            self._next_request_id += 1
            return True

        t_pad = self._prefill_pad(len(prompt), bucket)
        tokens = np.full((1, t_pad), int(self.config.pad_value), np.int32)
        tokens[0, :len(prompt)] = prompt
        lengths = np.array([len(prompt)], np.int32)
        pool.staging, first = self._prefill_c(
            pool.staging, self.params, jnp.asarray(tokens),
            jnp.asarray(lengths))
        pool.cache = self._migrate_c(pool.cache, pool.staging,
                                     jnp.asarray(0, jnp.int32),
                                     jnp.asarray(slot_idx, jnp.int32))
        self.metrics.record_admission(len(prompt), 0)
        self.metrics.observe("ttft", time.perf_counter() - t_submit)

        slot = _Slot(request_id=self._next_request_id, future=fut,
                     pos=len(prompt), token=int(np.asarray(first)[0]),
                     max_new=max_new, eos_id=eos, prompt=prompt)
        self._next_request_id += 1
        slot.generated.append(slot.token)
        pool.slots[slot_idx] = slot
        self._maybe_retire(pool, slot_idx)
        return True

    def _admit_one_paged(self, pool: _PagedPool) -> bool:
        """Paged admission: reserve EVERY page the sequence can ever
        touch up front (decode crossing a page boundary must find the
        page already mapped — a sentinel there silently drops the
        token's K/V), mapping the trie's committed prefix pages in place
        of the bucketed layout's restore copies.  Defers (returns False,
        request stays queued) when the arena cannot make room."""
        prompt, max_new, eos, fut, t_submit = self._pending[0]
        prefix_len, nodes = 0, []
        if pool.trie is not None:
            # cap below len(prompt): at least one real token must run
            # through prefill so the finishing chunk produces logits
            prefix_len, nodes = pool.trie.match(
                prompt, max_tokens=len(prompt) - 1)
            pool.trie.pin(nodes)  # survive make_room's evictions
        n_need = pool.pages_needed(len(prompt), max_new)
        if not pool.make_room(n_need - len(nodes)):
            if pool.trie is not None:
                pool.trie.unpin(nodes)
            return False
        self._pending.popleft()
        if fut.set_running_or_notify_cancel() is False:
            if pool.trie is not None:
                pool.trie.unpin(nodes)
            return True  # cancelled while queued; nothing reserved yet
        slot_idx = pool.free.pop()
        row = pool.free_rows.pop()
        # zero-copy restore: the slot's leading windows point at the
        # trie's pages (shared, read-only by construction — writes only
        # land past the prefix); the bucketed path would
        # dynamic_update_slice-copy these bytes into staging here
        for j, node in enumerate(nodes):
            pid = node.kv["page"]
            pool.pool.share(pid)
            pool.table.map(slot_idx, j, pid)
        for j in range(len(nodes), n_need):
            pool.table.map(slot_idx, j, pool.pool.alloc())
        if nodes:
            self.metrics.record_copy_on_restore_saved(
                len(nodes) * pool.page_bytes)
        self.metrics.record_admission(len(prompt), prefix_len)
        pool.jobs[row] = _PrefillJob(
            request_id=self._next_request_id, future=fut, prompt=prompt,
            max_new=max_new, eos_id=eos, row=row, slot_idx=slot_idx,
            start=prefix_len, prefix_nodes=nodes, t_submit=t_submit)
        self._next_request_id += 1
        return True

    # ----------------------------------------------------- chunked prefill
    def _prefill_round(self, pool, max_chunks: int) -> int:
        """Run up to `max_chunks` batched chunk calls on `pool`'s staging
        rows; finished jobs commit to the trie, migrate to their slot, and
        free their row.  Returns the number of chunk calls executed."""
        import jax
        import jax.numpy as jnp

        if self._paged:
            return self._prefill_round_paged(pool, max_chunks)
        calls = 0
        c_len = pool.chunk
        while pool.jobs and calls < max_chunks:
            tokens = np.full((pool.n_rows, c_len),
                             int(self.config.pad_value), np.int32)
            start = np.zeros((pool.n_rows,), np.int32)
            lengths = np.ones((pool.n_rows,), np.int32)
            for row, job in pool.jobs.items():
                seg = job.prompt[job.start:job.start + c_len]
                tokens[row, :len(seg)] = seg
                start[row] = job.start
                lengths[row] = len(job.prompt)
            args = (pool.staging, self.params, jnp.asarray(tokens),
                    jnp.asarray(start), jnp.asarray(lengths))
            result = self._prefill_chunk_c.get_compiled(*args)
            if pool.bucket not in self._audited_prefill:
                self._audited_prefill.add(pool.bucket)
                self._audit_chunked_prefill(result, pool.bucket)
            t0 = time.perf_counter()
            pool.staging, first = result.tree_jitted(*args)
            first = np.asarray(jax.block_until_ready(first))
            self.metrics.record_prefill_chunk(
                pool.n_rows, c_len, time.perf_counter() - t0)
            calls += 1
            for row in list(pool.jobs):
                job = pool.jobs[row]
                job.start += c_len
                if job.start >= len(job.prompt):
                    self._finish_prefill(pool, row, int(first[row]))
        return calls

    def _prefill_round_paged(self, pool: _PagedPool,
                             max_chunks: int) -> int:
        """Paged `_prefill_round`: each chunk writes straight into the
        arena through the job's table row (no staging, no migrate, and a
        restored prefix needed no copy to begin with).  Idle rows get an
        all-sentinel table row so their writes drop and their logits are
        garbage nobody reads — one compiled signature regardless of
        which rows are live."""
        import jax
        import jax.numpy as jnp

        calls = 0
        c_len = pool.chunk
        while pool.jobs and calls < max_chunks:
            tokens = np.full((pool.n_rows, c_len),
                             int(self.config.pad_value), np.int32)
            start = np.zeros((pool.n_rows,), np.int32)
            lengths = np.ones((pool.n_rows,), np.int32)
            tbl = np.full((pool.n_rows, pool.max_pages),
                          pool.pool.sentinel, np.int32)
            for row, job in pool.jobs.items():
                seg = job.prompt[job.start:job.start + c_len]
                tokens[row, :len(seg)] = seg
                start[row] = job.start
                lengths[row] = len(job.prompt)
                tbl[row] = pool.table.array[job.slot_idx]
            args = (pool.arena, self.params, jnp.asarray(tbl),
                    jnp.asarray(tokens), jnp.asarray(start),
                    jnp.asarray(lengths))
            result = self._paged_c("chunk").get_compiled(*args)
            if pool.bucket not in self._audited_prefill:
                self._audited_prefill.add(pool.bucket)
                # SERVE002's jaxpr walk asserts the bucketed staging
                # idiom (dynamic_update_slice restore); the paged
                # program replaces it with table writes, audited
                # host-side by KV001 — only the donation half applies
                try:
                    from easydist_tpu.analyze import check_decode_donation

                    check_decode_donation(
                        result,
                        node=f"prefill_chunk_paged[cap={pool.bucket}]")
                except ImportError:
                    pass
            t0 = time.perf_counter()
            pool.arena, first = result.tree_jitted(*args)
            first = np.asarray(jax.block_until_ready(first))
            self.metrics.record_prefill_chunk(
                pool.n_rows, c_len, time.perf_counter() - t0)
            calls += 1
            for row in list(pool.jobs):
                job = pool.jobs[row]
                job.start += c_len
                if job.start >= len(job.prompt):
                    self._finish_prefill_paged(pool, row,
                                               int(first[row]))
        return calls

    def _finish_prefill_paged(self, pool: _PagedPool, row: int,
                              first_token: int) -> None:
        """One paged job's last chunk ran: commit its whole-chunk pages
        into the trie as page REFERENCES (share + {"page": id} — no
        extraction copy), free the row, open the decode slot."""
        job = pool.jobs.pop(row)
        pinned = list(job.prefix_nodes)
        if pool.trie is not None:
            nodes = list(job.prefix_nodes)
            for j in range(len(nodes), len(job.prompt) // pool.chunk):
                chunk_toks = job.prompt[j * pool.chunk:
                                        (j + 1) * pool.chunk]
                node = pool.trie.lookup_node(nodes, chunk_toks)
                if node is None:
                    pid = int(pool.table.array[job.slot_idx, j])
                    pool.pool.share(pid)       # the trie's hold
                    node = pool.trie.commit(nodes, chunk_toks,
                                            {"page": pid},
                                            nbytes=pool.page_bytes)
                    if node is None:
                        pool.pool.release(pid)  # budget refused it
                if node is None:
                    break  # byte budget exhausted; partial path is fine
                nodes.append(node)
            pool.trie.unpin(job.prefix_nodes)
            pool.trie.pin(nodes)
            pinned = nodes
            self._audit_prefix_cache(pool)
        pool.free_rows.append(row)
        self.metrics.observe("ttft", time.perf_counter() - job.t_submit)

        slot = _Slot(request_id=job.request_id, future=job.future,
                     pos=len(job.prompt), token=first_token,
                     max_new=job.max_new, eos_id=job.eos_id,
                     pinned=pinned, prompt=job.prompt)
        slot.generated.append(slot.token)
        pool.slots[job.slot_idx] = slot
        self._maybe_retire(pool, job.slot_idx)

    def _finish_prefill(self, pool: _BucketPool, row: int,
                        first_token: int) -> None:
        """One job's last chunk ran: commit its aligned chunks into the
        trie, migrate the staging row into the reserved pool slot, free
        the row, and open the decode slot."""
        import jax.numpy as jnp

        job = pool.jobs.pop(row)
        pinned = list(job.prefix_nodes)
        if pool.trie is not None:
            nodes = list(job.prefix_nodes)
            for j in range(len(nodes), len(job.prompt) // pool.chunk):
                chunk_toks = job.prompt[j * pool.chunk:(j + 1) * pool.chunk]
                node = pool.trie.lookup_node(nodes, chunk_toks)
                if node is None:
                    kv = self._extract_for(pool.chunk)(
                        pool.staging, jnp.asarray(row, jnp.int32),
                        jnp.asarray(j * pool.chunk, jnp.int32))
                    node = pool.trie.commit(nodes, chunk_toks, kv)
                if node is None:
                    break  # byte budget exhausted; partial path is fine
                nodes.append(node)
            # hold the full committed path for the slot's lifetime
            pool.trie.unpin(job.prefix_nodes)
            pool.trie.pin(nodes)
            pinned = nodes
            self._audit_prefix_cache(pool)
        pool.cache = self._migrate_c(pool.cache, pool.staging,
                                     jnp.asarray(row, jnp.int32),
                                     jnp.asarray(job.slot_idx, jnp.int32))
        pool.free_rows.append(row)
        self.metrics.observe("ttft", time.perf_counter() - job.t_submit)

        slot = _Slot(request_id=job.request_id, future=job.future,
                     pos=len(job.prompt), token=first_token,
                     max_new=job.max_new, eos_id=job.eos_id,
                     pinned=pinned, prompt=job.prompt)
        slot.generated.append(slot.token)
        pool.slots[job.slot_idx] = slot
        self._maybe_retire(pool, job.slot_idx)

    # ------------------------------------------------------------- decoding
    def _retire(self, pool, slot_idx: int, reason: str) -> None:
        slot = pool.slots.pop(slot_idx)
        pool.free.append(slot_idx)
        if self._paged:
            for pid in pool.table.unmap_row(slot_idx):
                pool.pool.release(pid)
        if pool.trie is not None and slot.pinned:
            pool.trie.unpin(slot.pinned)
        if self._paged:
            self._audit_kv(pool, f"retire[{reason}]")
        slot.future.set_result({"ids": list(slot.generated),
                                "finish_reason": reason})
        self.metrics.inc("requests_completed")

    def _maybe_retire(self, pool: _BucketPool, slot_idx: int) -> bool:
        slot = pool.slots[slot_idx]
        if slot.eos_id is not None and slot.token == slot.eos_id:
            self._retire(pool, slot_idx, "eos")
        elif len(slot.generated) >= slot.max_new:
            self._retire(pool, slot_idx, "length")
        elif slot.pos >= pool.bucket:
            self._retire(pool, slot_idx, "bucket_full")
        else:
            return False
        return True

    def _decode_round(self, pool) -> None:
        """One compiled decode step over ALL slots of `pool` (fixed
        shapes: the signature cache stays at one entry per bucket — and
        at ONE entry total for the paged layout, whose only per-step
        variation is page-table DATA)."""
        import jax
        import jax.numpy as jnp

        token = np.zeros((pool.n_slots,), np.int32)
        pos = np.zeros((pool.n_slots,), np.int32)
        for idx, slot in pool.slots.items():
            token[idx] = slot.token
            pos[idx] = slot.pos
        if self._paged:
            # only actively-decoding rows expose their table row: a
            # reserved-but-still-prefilling slot's pages (possibly
            # SHARED prefix pages) must not take the dead-row write this
            # step lands at pos 0 — sentinel rows drop it instead
            tbl = np.full((pool.n_slots, pool.max_pages),
                          pool.pool.sentinel, np.int32)
            for idx in pool.slots:
                tbl[idx] = pool.table.array[idx]
            args = (pool.arena, self.params, jnp.asarray(tbl),
                    jnp.asarray(token), jnp.asarray(pos))
            compiled = self._paged_c("decode")
        else:
            args = (pool.cache, self.params, jnp.asarray(token),
                    jnp.asarray(pos))
            compiled = self._decode_c
        result = compiled.get_compiled(*args)
        if pool.bucket not in self._audited:
            self._audited.add(pool.bucket)
            self._audit_donation(result, pool.bucket)
            if self._paged:
                self._audit_kv(pool, "first_decode")
        t0 = time.perf_counter()
        if self._paged:
            pool.arena, nxt = result.tree_jitted(*args)
        else:
            pool.cache, nxt = result.tree_jitted(*args)
        nxt = np.asarray(jax.block_until_ready(nxt))
        dt = time.perf_counter() - t0
        n_active = pool.n_active
        for idx in list(pool.slots):
            slot = pool.slots[idx]
            slot.token = int(nxt[idx])
            slot.pos += 1
            slot.generated.append(slot.token)
            self._maybe_retire(pool, idx)
        self.metrics.record_decode_step(n_active, pool.n_slots, dt)
        if self._paged:
            in_use, tokens = pool.occupancy()
            self.metrics.record_kv_pool(in_use, tokens, pool.chunk)

    def _audit_donation(self, result, bucket: int) -> None:
        try:
            from easydist_tpu.analyze import check_decode_donation

            check_decode_donation(result, node=f"decode[bucket={bucket}]")
        except ImportError:  # analyze is an optional layer at runtime
            pass

    def _audit_chunked_prefill(self, result, bucket: int) -> None:
        try:
            from easydist_tpu.analyze import check_chunked_prefill

            check_chunked_prefill(result,
                                  node=f"prefill_chunk[bucket={bucket}]")
        except ImportError:
            pass

    def _audit_prefix_cache(self, pool) -> None:
        try:
            from easydist_tpu.analyze import check_prefix_cache

            check_prefix_cache(pool.trie,
                               node=f"prefix_cache[bucket={pool.bucket}]")
        except ImportError:
            pass

    def _audit_kv(self, pool: _PagedPool, where: str) -> None:
        """KV001: page-table/refcount audit at the state transitions
        where drift would matter (first decode, every retire)."""
        try:
            from easydist_tpu.analyze import check_page_table

            check_page_table(pool.pool, pool.table, trie=pool.trie,
                             node=f"kv[{where}]")
        except ImportError:  # analyze is an optional layer at runtime
            pass

    # ------------------------------------------------------------- driving
    def step(self) -> int:
        """One serving round: admit pending prompts into free slots/rows,
        run at most `prefill_chunks_per_step` prefill chunk calls, then
        one decode step per bucket with live slots, harvesting
        retirements.  Returns the number of tokens generated this round
        (decode tokens; prefill first-tokens count via `prefills`)."""
        # the replica-death fault point sits at the step boundary: tokens
        # from completed steps were already streamed/synced, this step's
        # are lost — exactly the state a real mid-decode crash leaves
        faultinject.crash_point("fleet.replica.crash")
        while self._admit_one():
            pass
        if self._chunked or self._paged:
            budget = self.config.prefill_chunks_per_step
            for pool in self._pools.values():
                if budget <= 0:
                    break
                if pool.jobs:
                    budget -= self._prefill_round(pool, budget)
        before = self.metrics.counter("tokens_generated")
        for pool in self._pools.values():
            if pool.slots:
                self._decode_round(pool)
        self.metrics.set_gauge("queue_depth", self.queue_depth)
        return self.metrics.counter("tokens_generated") - before

    def run_until_drained(self, max_steps: int = 100000) -> None:
        """Drive `step()` until no request is live or queued."""
        for _ in range(max_steps):
            if not self._pending and not any(
                    p.slots or p.jobs for p in self._pools.values()):
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # ------------------------------------------------------------ lifecycle
    def drain(self, wait: bool = True, max_steps: int = 100000):
        """Stop admitting (submits raise `ReplicaDrainingError`), let
        in-flight work retire, and export the tries' hot pages for
        re-admission elsewhere.  `wait=False` only flips the flag — the
        caller keeps driving `step()` (a fleet router does this so its
        OTHER replicas never stall behind this one's drain) and calls
        `export_hot_pages()` itself once `is_drained`.  Returns the hot
        pages (wait=True) or None (wait=False).  Idempotent."""
        self._draining = True
        if not wait:
            return None
        self.run_until_drained(max_steps=max_steps)
        return self.export_hot_pages()

    @property
    def is_draining(self) -> bool:
        return self._draining

    @property
    def is_drained(self) -> bool:
        """No queued, prefilling, or decoding work left."""
        return not self._pending and not any(
            p.slots or p.jobs for p in self._pools.values())

    def export_hot_pages(self) -> Dict[int, List[List[tuple]]]:
        """Per-bucket root-to-leaf chunk paths from each trie,
        hottest-first (prefix_cache.hot_paths) — what a router re-imports
        into surviving replicas on drain so shared-prefix traffic does
        not re-pay prefill after a scale-down."""
        return {b: ([self._materialize_path(p, path)
                     for path in p.trie.hot_paths()]
                    if self._paged else p.trie.hot_paths())
                for b, p in self._pools.items() if p.trie is not None}

    # ------------------------------------------------- fleet trie access
    def _trie_bucket(self, bucket: Optional[int]) -> Optional[int]:
        """The pool key `bucket` maps to: itself, or the single paged
        pool's capacity cap."""
        if bucket is None:
            return None
        return max(self.config.decode_buckets) if self._paged else bucket

    def _materialize_path(self, pool, path: List[tuple]) -> List[tuple]:
        """Fleet transport of paged trie entries: replace {"page": id}
        references with the page's actual K/V (the same
        [layers, heads, chunk, head_dim] arrays a bucketed trie commits),
        so exported paths are layout-agnostic on the wire."""
        import jax.numpy as jnp

        out = []
        for key, kv in path:
            if isinstance(kv, dict) and set(kv) == {"page"}:
                kv = self._paged_c("export")(
                    pool.arena, jnp.asarray(int(kv["page"]), jnp.int32))
            out.append((key, kv))
        return out

    def _import_path_paged(self, pool, path: Sequence[tuple]) -> int:
        """Commit a transported (materialized) chunk path into the paged
        trie: each chunk lands in a freshly allocated arena page, written
        by the compiled import program and committed as a page
        reference.  First-commit-wins like `PrefixCache.import_path`;
        stops when the arena or the trie budget refuses a page."""
        import jax.numpy as jnp

        nodes: List[object] = []
        for key, kv in path:
            node = pool.trie.lookup_node(nodes, key)
            if node is None:
                if not pool.make_room(1):
                    break
                pid = pool.pool.alloc()
                pool.arena = self._paged_c("import")(
                    pool.arena, kv, jnp.asarray(pid, jnp.int32))
                node = pool.trie.commit(nodes, key, {"page": pid},
                                       nbytes=pool.page_bytes)
                if node is None:
                    pool.pool.release(pid)
                    break
            nodes.append(node)
        return len(nodes)

    def bucket_chunk(self, prompt: Sequence[int]) -> Optional[int]:
        """Trie page size (tokens) for the bucket `prompt` decodes in, or
        None when the prompt fits no bucket / prefix reuse is off."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        if bucket is None or not (self._chunked or self._paged) \
                or not self.config.enable_prefix_cache \
                or not self.config.prefix_cache_bytes:
            return None
        return min(self.config.prefill_chunk, self._trie_bucket(bucket))

    def prefix_affinity(self, prompt: Sequence[int]) -> int:
        """Tokens of `prompt` already committed in this session's trie —
        non-mutating (PrefixCache.peek), so a router can probe every
        replica without disturbing LRU state."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pools.get(self._trie_bucket(bucket)) \
            if bucket is not None else None
        if pool is None or pool.trie is None:
            return 0
        return pool.trie.peek(prompt, max_tokens=len(prompt) - 1)

    def export_prefix_path(self, prompt: Sequence[int],
                           max_tokens: Optional[int] = None) -> List[tuple]:
        """Committed chunk path for `prompt`'s longest cached prefix, as
        [(chunk_tokens, kv)] for transport to another replica (paged
        sessions materialize their page references into real arrays)."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pools.get(self._trie_bucket(bucket)) \
            if bucket is not None else None
        if pool is None or pool.trie is None:
            return []
        path = pool.trie.export_path(prompt, max_tokens=max_tokens)
        return self._materialize_path(pool, path) if self._paged else path

    def import_prefix_path(self, prompt: Sequence[int],
                           path: Sequence[tuple]) -> int:
        """Commit a transported chunk path into the trie of the bucket
        `prompt` will decode in (creating the pool if needed).  Returns
        chunks present along the path afterwards."""
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        if bucket is None:
            return 0
        pool = self._pool_for(bucket)
        if pool.trie is None:
            return 0
        if self._paged:
            return self._import_path_paged(pool, path)
        return pool.trie.import_path(path)

    def import_hot_pages(self, pages: Dict[int, List[List[tuple]]]) -> int:
        """Re-admit another replica's exported hot pages (drain
        migration): each bucket's paths import into this session's same
        bucket when configured here, falling back to the largest
        configured bucket.  Returns total chunks committed."""
        total = 0
        for bucket, paths in pages.items():
            b = bucket if bucket in self.config.decode_buckets \
                else max(self.config.decode_buckets)
            pool = self._pool_for(b)
            if pool.trie is None:
                continue
            for path in paths:
                total += (self._import_path_paged(pool, path)
                          if self._paged else pool.trie.import_path(path))
        return total

    def snapshot_inflight(self) -> List[Dict[str, object]]:
        """Progress of every live request, keyed by its future (identity
        — the only handle a router shares with this session).  `ids` is
        the tokens already emitted, i.e. what a streaming client has
        already received; a router syncs these into per-request
        `ResumeDescriptor`s after each step so a crash of THIS session
        can be recovered bitwise by resubmitting prompt+ids elsewhere.
        Read-only: no session state changes."""
        out: List[Dict[str, object]] = []
        for prompt, max_new, eos, fut, _t in self._pending:
            out.append({"future": fut, "prompt": list(prompt), "ids": [],
                        "max_new": max_new, "eos_id": eos,
                        "stage": "queued"})
        for pool in self._pools.values():
            for job in pool.jobs.values():
                out.append({"future": job.future,
                            "prompt": list(job.prompt), "ids": [],
                            "max_new": job.max_new, "eos_id": job.eos_id,
                            "stage": "prefill"})
            for slot in pool.slots.values():
                out.append({"future": slot.future,
                            "prompt": list(slot.prompt),
                            "ids": list(slot.generated),
                            "max_new": slot.max_new,
                            "eos_id": slot.eos_id, "stage": "decode"})
        return out

    def evacuate(self) -> List[Dict[str, object]]:
        """Preemptive drain (SIGTERM grace too short to retire decodes):
        retire EVERY live request immediately with finish_reason
        "evacuated" and partial ids, returning resume descriptors.  A
        router resubmits prompt + ids with the remaining budget elsewhere;
        greedy continuation is a pure function of the token prefix, so the
        concatenated output is bitwise-identical to an uninterrupted run.
        An evacuated partial never contains eos (eos retires the slot the
        step it appears) and is always shorter than max_new (reaching it
        retires as "length"), so the remaining budget is >= 1."""
        self._draining = True
        out: List[Dict[str, object]] = []
        while self._pending:
            prompt, max_new, eos, fut, _ = self._pending.popleft()
            if fut.set_running_or_notify_cancel() is False:
                continue
            fut.set_result({"ids": [], "finish_reason": "evacuated"})
            out.append({"prompt": list(prompt), "ids": [],
                        "max_new": max_new, "eos_id": eos})
        for pool in self._pools.values():
            for row in list(pool.jobs):
                job = pool.jobs.pop(row)
                pool.free_rows.append(row)
                pool.free.append(job.slot_idx)
                if self._paged:
                    for pid in pool.table.unmap_row(job.slot_idx):
                        pool.pool.release(pid)
                if pool.trie is not None:
                    pool.trie.unpin(job.prefix_nodes)
                job.future.set_result(
                    {"ids": [], "finish_reason": "evacuated"})
                out.append({"prompt": list(job.prompt), "ids": [],
                            "max_new": job.max_new, "eos_id": job.eos_id})
            for idx in list(pool.slots):
                slot = pool.slots[idx]
                desc = {"prompt": list(slot.prompt),
                        "ids": list(slot.generated),
                        "max_new": slot.max_new, "eos_id": slot.eos_id}
                self._retire(pool, idx, "evacuated")
                out.append(desc)
        return out

    def close(self) -> None:
        """Drain, then release the pooled device caches.  Idempotent;
        every submit afterwards raises `ReplicaDrainingError`."""
        if self._closed:
            return
        self.drain(wait=True)
        self._closed = True
        self._pools.clear()

    # ----------------------------------------------------------- reporting
    def stats(self) -> Dict[str, object]:
        return {
            "replica_id": self.replica_id,
            "draining": self._draining,
            "queue_depth": self.queue_depth,
            "pending": len(self._pending),
            "buckets": {
                b: {"active": p.n_active, "free": len(p.free),
                    "prefilling": len(p.jobs),
                    "free_rows": len(p.free_rows),
                    "prefix_cache": (p.trie.stats() if p.trie else None),
                    **({"kv_pool": p.pool.stats(),
                        "kv_table_mapped": int(
                            (p.table.array != p.table.sentinel).sum())}
                       if self._paged else {})}
                for b, p in self._pools.items()},
            "decode_signatures": (
                self._paged_cs["decode"].cache_stats()
                if self._paged and "decode" in self._paged_cs
                else self._decode_c.cache_stats()),
            "prefill_signatures": (
                self._paged_cs["chunk"].cache_stats()
                if self._paged and "chunk" in self._paged_cs
                else (self._prefill_chunk_c if self._chunked
                      else self._prefill_c).cache_stats()),
            "migrate_signatures": self._migrate_c.cache_stats(),
            "metrics": self.metrics.snapshot(),
        }

    # --------------------------------------------------------- constructors
    @classmethod
    def for_gpt(cls, params, cfg, **kw):
        """Session over models/gpt.py; decode_buckets must fit cfg.seq
        (the learned-position-table bound)."""
        import dataclasses

        from easydist_tpu.models import gpt

        kw.setdefault("compile_key", ("gpt", dataclasses.astuple(cfg)))
        return cls(
            params,
            model_prefill=lambda p, c, t, l: gpt.gpt_prefill(p, cfg, c, t, l),
            model_prefill_chunk=lambda p, c, t, s, l: gpt.gpt_prefill_chunk(
                p, cfg, c, t, s, l),
            model_decode=lambda p, c, t, pos: gpt.gpt_decode_step(
                p, cfg, c, t, pos),
            init_cache=lambda b, L, dt=None: gpt.init_kv_cache(
                cfg, b, L, dtype=dt),
            model_prefill_chunk_paged=lambda p, pg, tb, t, s, l:
                gpt.gpt_prefill_chunk_paged(p, cfg, pg, tb, t, s, l),
            model_decode_paged=lambda p, pg, tb, t, pos:
                gpt.gpt_decode_step_paged(p, cfg, pg, tb, t, pos),
            init_pages=lambda n, t, dt=None: gpt.init_kv_pages(
                cfg, n, t, dtype=dt),
            max_prompt_len=cfg.seq, **kw)

    @classmethod
    def for_llama(cls, params, cfg, **kw):
        """Session over models/llama.py (RoPE: buckets are not bound by
        cfg.seq)."""
        import dataclasses

        from easydist_tpu.models import llama

        kw.setdefault("compile_key", ("llama", dataclasses.astuple(cfg)))
        return cls(
            params,
            model_prefill=lambda p, c, t, l: llama.llama_prefill(
                p, cfg, c, t, l),
            model_prefill_chunk=lambda p, c, t, s, l:
                llama.llama_prefill_chunk(p, cfg, c, t, s, l),
            model_decode=lambda p, c, t, pos: llama.llama_decode_step(
                p, cfg, c, t, pos),
            init_cache=lambda b, L, dt=None: llama.init_kv_cache(
                cfg, b, L, dtype=dt),
            model_prefill_chunk_paged=lambda p, pg, tb, t, s, l:
                llama.llama_prefill_chunk_paged(p, cfg, pg, tb, t, s, l),
            model_decode_paged=lambda p, pg, tb, t, pos:
                llama.llama_decode_step_paged(p, cfg, pg, tb, t, pos),
            init_pages=lambda n, t, dt=None: llama.init_kv_pages(
                cfg, n, t, dtype=dt),
            **kw)
