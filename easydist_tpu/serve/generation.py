"""Token-level decode serving: `GenerationSession`.

`ServeEngine` serves request-shaped functions — every call re-runs the
whole forward.  For autoregressive generation that is O(T^2) attention
flops per sequence; the KV cache makes each token O(T).  This module is
the serving half of the cache-carrying model API
(models/gpt.py::gpt_prefill/gpt_decode_step and the llama mirror):

  * **prefill/decode split** — each admitted prompt runs one prefill
    through a closed set of padded prompt lengths (powers of two, capped
    at the decode bucket) into a single-row staging cache, then migrates
    into a slot of the bucket's pooled cache with one
    `dynamic_update_slice`;
  * **bucketed KV pool** — one slot pool per `ServeConfig.decode_buckets`
    entry, shaped [layers, max_decode_slots, heads, bucket, head_dim].
    Slots are recycled through a free list as requests retire (EOS /
    max-new-tokens / bucket exhausted), so admission is continuous;
  * **one compiled decode step** — decode always steps ALL slots of a
    pool (idle rows are throwaway work the occupancy gauge accounts
    for), so token/pos arrays have a fixed shape and the jaxfront
    signature cache holds exactly one decode executable per bucket, for
    every token of every request;
  * **donated cache** — the pool is positional arg 0 of the compiled
    step and the first output, so `infer_state_io` pairs and donates it:
    XLA updates the cache in place instead of copying
    layers*slots*bucket*dim bytes per token.  `analyze.SERVE001` audits
    exactly this property after the first decode compile.

Sharding rides the existing solver: the cache's heads axis (dim 2) is the
tensor-parallel shard dim, matching the attention strategy the solver
picks for the model itself, so tp serving works unchanged —
`kv_cache_specs` names the placement for callers that want to lay the
pool out explicitly.
"""

from __future__ import annotations

import collections
import logging
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .admission import RequestTooLargeError
from .batcher import select_bucket
from .engine import ServeConfig
from .metrics import ServeMetrics

logger = logging.getLogger(__name__)


def kv_cache_specs(axis: str = "tp"):
    """PartitionSpec pytree for a KV cache {"k", "v"} of shape
    [layers, batch/slots, heads, max_len, head_dim]: heads sharded on
    `axis`, everything else replicated — the placement consistent with a
    tensor-parallel attention strategy."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis, None, None)
    return {"k": spec, "v": spec}


@dataclass
class _Slot:
    """Host-side view of one pooled decode row."""
    request_id: int
    future: Future
    pos: int                      # next cache write position
    token: int                    # last generated token (not yet in cache)
    max_new: int
    eos_id: Optional[int]
    generated: List[int] = field(default_factory=list)


class _BucketPool:
    """One decode bucket: pooled cache + free-list slot allocator +
    single-row staging cache reused across prefills."""

    def __init__(self, bucket: int, n_slots: int, init_cache):
        self.bucket = bucket
        self.n_slots = n_slots
        self.cache = init_cache(n_slots, bucket)
        self.staging = init_cache(1, bucket)
        self.free: List[int] = list(range(n_slots))
        self.slots: Dict[int, _Slot] = {}          # slot index -> _Slot

    @property
    def n_active(self) -> int:
        return len(self.slots)


class GenerationSession:
    """Continuous-batching token generation over a cache-carrying model.

    model_prefill(params, cache, tokens, lengths) -> (cache, logits)
    model_decode(params, cache, token, pos) -> (cache, logits)
    init_cache(batch, max_len, dtype=None) -> cache pytree

    Greedy decoding (argmax inside the compiled step, so only int32 token
    ids cross the host boundary per token).  `submit` returns a Future
    resolving to {"ids": [...generated ids...], "finish_reason":
    "eos"|"length"|"bucket_full"}; drive with `step()` (one admit +
    decode + harvest round) or `run_until_drained()`.
    """

    def __init__(self, params, *, model_prefill: Callable,
                 model_decode: Callable, init_cache: Callable,
                 config: Optional[ServeConfig] = None, mesh=None,
                 eos_id: Optional[int] = None,
                 max_prompt_len: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None):
        from easydist_tpu.jaxfront import easydist_compile

        self.config = config or ServeConfig()
        if max_prompt_len is not None:
            bad = [b for b in self.config.decode_buckets
                   if b > max_prompt_len]
            if bad:
                raise ValueError(
                    f"decode_buckets {bad} exceed the model's maximum "
                    f"sequence length {max_prompt_len}; set "
                    f"ServeConfig(decode_buckets=...) within it")
        self.params = params
        self.mesh = mesh
        self.eos_id = eos_id
        self.metrics = metrics or ServeMetrics()
        self._init_cache = init_cache
        self._pending: collections.deque = collections.deque()
        self._pools: Dict[int, _BucketPool] = {}
        self._next_request_id = 0
        self._audited: set = set()

        def _prefill(cache, params, tokens, lengths):
            import jax.numpy as jnp

            cache, logits = model_prefill(params, cache, tokens, lengths)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _migrate(pool, cache, slot):
            import jax

            return {
                k: jax.lax.dynamic_update_slice(
                    pool[k], cache[k].astype(pool[k].dtype),
                    (0, slot, 0, 0, 0))
                for k in ("k", "v")
            }

        def _decode(pool, params, token, pos):
            import jax.numpy as jnp

            pool, logits = model_decode(params, pool, token, pos)
            return pool, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # pool/cache is arg 0 and output 0 of every compiled callable, so
        # state_io="auto" pairs it and XLA gets the buffer donated
        self._prefill_c = easydist_compile(_prefill, mesh=mesh)
        self._migrate_c = easydist_compile(_migrate, mesh=mesh)
        self._decode_c = easydist_compile(_decode, mesh=mesh)

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: int = 16,
               eos_id: Optional[int] = None) -> Future:
        """Queue one prompt; generation interleaves with every other live
        request (continuous batching) as `step()` is driven."""
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if select_bucket(len(prompt) + 1, self.config.decode_buckets) is None:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens does not fit any decode "
                f"bucket {self.config.decode_buckets} with room to "
                f"generate")
        fut = Future()
        self._pending.append(
            (prompt, max_new_tokens,
             self.eos_id if eos_id is None else eos_id, fut))
        self.metrics.inc("requests_submitted")
        return fut

    # ------------------------------------------------------------- plumbing
    def _pool_for(self, bucket: int) -> _BucketPool:
        pool = self._pools.get(bucket)
        if pool is None:
            pool = _BucketPool(bucket, self.config.max_decode_slots,
                               self._cache_factory)
            self._pools[bucket] = pool
        return pool

    def _cache_factory(self, batch: int, max_len: int):
        dtype = self.config.kv_cache_dtype
        return self._init_cache(batch, max_len,
                                None if dtype == "auto" else dtype)

    def _prefill_pad(self, plen: int, bucket: int) -> int:
        """Smallest power of two >= plen (floor 8), capped at the decode
        bucket — the closed set of prefill signatures per bucket."""
        t = 8
        while t < plen:
            t *= 2
        return min(t, bucket)

    def _admit_one(self) -> bool:
        """Pop one pending request into a free slot: prefill + migrate.
        Returns False when nothing is admissible."""
        import jax.numpy as jnp

        if not self._pending:
            return False
        prompt, max_new, eos, fut = self._pending[0]
        bucket = select_bucket(len(prompt) + 1, self.config.decode_buckets)
        pool = self._pool_for(bucket)
        if not pool.free:
            return False
        self._pending.popleft()
        if fut.set_running_or_notify_cancel() is False:
            return True  # cancelled while queued; slot stays free
        slot_idx = pool.free.pop()

        t_pad = self._prefill_pad(len(prompt), bucket)
        tokens = np.full((1, t_pad), int(self.config.pad_value), np.int32)
        tokens[0, :len(prompt)] = prompt
        lengths = np.array([len(prompt)], np.int32)
        pool.staging, first = self._prefill_c(
            pool.staging, self.params, jnp.asarray(tokens),
            jnp.asarray(lengths))
        pool.cache = self._migrate_c(pool.cache, pool.staging,
                                     jnp.asarray(slot_idx, jnp.int32))
        self.metrics.inc("prefills")

        slot = _Slot(request_id=self._next_request_id, future=fut,
                     pos=len(prompt), token=int(np.asarray(first)[0]),
                     max_new=max_new, eos_id=eos)
        self._next_request_id += 1
        slot.generated.append(slot.token)
        pool.slots[slot_idx] = slot
        self._maybe_retire(pool, slot_idx)
        return True

    def _retire(self, pool: _BucketPool, slot_idx: int, reason: str) -> None:
        slot = pool.slots.pop(slot_idx)
        pool.free.append(slot_idx)
        slot.future.set_result({"ids": list(slot.generated),
                                "finish_reason": reason})
        self.metrics.inc("requests_completed")

    def _maybe_retire(self, pool: _BucketPool, slot_idx: int) -> bool:
        slot = pool.slots[slot_idx]
        if slot.eos_id is not None and slot.token == slot.eos_id:
            self._retire(pool, slot_idx, "eos")
        elif len(slot.generated) >= slot.max_new:
            self._retire(pool, slot_idx, "length")
        elif slot.pos >= pool.bucket:
            self._retire(pool, slot_idx, "bucket_full")
        else:
            return False
        return True

    def _decode_round(self, pool: _BucketPool) -> None:
        """One compiled decode step over ALL slots of `pool` (fixed
        shapes: the signature cache stays at one entry per bucket)."""
        import jax
        import jax.numpy as jnp

        token = np.zeros((pool.n_slots,), np.int32)
        pos = np.zeros((pool.n_slots,), np.int32)
        for idx, slot in pool.slots.items():
            token[idx] = slot.token
            pos[idx] = slot.pos
        args = (pool.cache, self.params, jnp.asarray(token),
                jnp.asarray(pos))
        result = self._decode_c.get_compiled(*args)
        if pool.bucket not in self._audited:
            self._audited.add(pool.bucket)
            self._audit_donation(result, pool.bucket)
        t0 = time.perf_counter()
        pool.cache, nxt = result.tree_jitted(*args)
        nxt = np.asarray(jax.block_until_ready(nxt))
        dt = time.perf_counter() - t0
        n_active = pool.n_active
        for idx in list(pool.slots):
            slot = pool.slots[idx]
            slot.token = int(nxt[idx])
            slot.pos += 1
            slot.generated.append(slot.token)
            self._maybe_retire(pool, idx)
        self.metrics.record_decode_step(n_active, pool.n_slots, dt)

    def _audit_donation(self, result, bucket: int) -> None:
        try:
            from easydist_tpu.analyze import check_decode_donation

            check_decode_donation(result, node=f"decode[bucket={bucket}]")
        except ImportError:  # analyze is an optional layer at runtime
            pass

    # ------------------------------------------------------------- driving
    def step(self) -> int:
        """One serving round: admit pending prompts into free slots, run
        one decode step per bucket with live slots, harvest retirements.
        Returns the number of tokens generated this round."""
        while self._admit_one():
            pass
        before = self.metrics.counter("tokens_generated")
        for pool in self._pools.values():
            if pool.slots:
                self._decode_round(pool)
        return self.metrics.counter("tokens_generated") - before

    def run_until_drained(self, max_steps: int = 100000) -> None:
        """Drive `step()` until no request is live or queued."""
        for _ in range(max_steps):
            if not self._pending and not any(
                    p.slots for p in self._pools.values()):
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # ----------------------------------------------------------- reporting
    def stats(self) -> Dict[str, object]:
        return {
            "pending": len(self._pending),
            "buckets": {
                b: {"active": p.n_active, "free": len(p.free)}
                for b, p in self._pools.items()},
            "decode_signatures": self._decode_c.cache_stats(),
            "prefill_signatures": self._prefill_c.cache_stats(),
            "migrate_signatures": self._migrate_c.cache_stats(),
            "metrics": self.metrics.snapshot(),
        }

    # --------------------------------------------------------- constructors
    @classmethod
    def for_gpt(cls, params, cfg, **kw):
        """Session over models/gpt.py; decode_buckets must fit cfg.seq
        (the learned-position-table bound)."""
        from easydist_tpu.models import gpt

        return cls(
            params,
            model_prefill=lambda p, c, t, l: gpt.gpt_prefill(p, cfg, c, t, l),
            model_decode=lambda p, c, t, pos: gpt.gpt_decode_step(
                p, cfg, c, t, pos),
            init_cache=lambda b, L, dt=None: gpt.init_kv_cache(
                cfg, b, L, dtype=dt),
            max_prompt_len=cfg.seq, **kw)

    @classmethod
    def for_llama(cls, params, cfg, **kw):
        """Session over models/llama.py (RoPE: buckets are not bound by
        cfg.seq)."""
        from easydist_tpu.models import llama

        return cls(
            params,
            model_prefill=lambda p, c, t, l: llama.llama_prefill(
                p, cfg, c, t, l),
            model_decode=lambda p, c, t, pos: llama.llama_decode_step(
                p, cfg, c, t, pos),
            init_cache=lambda b, L, dt=None: llama.init_kv_cache(
                cfg, b, L, dtype=dt),
            **kw)
