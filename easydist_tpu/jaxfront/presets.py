"""Preset (analytic) SPMD rules for common jax primitives.

Execution-based ShardCombine is the general mechanism, but the hot primitives
of any transformer/convnet have well-known sharding rules — computing them
analytically makes compile time independent of tensor sizes.  This is the
TPU analog of the reference's discovery-bypass rule bank
(easydist/torch/preset_propagation.py:32-378 and the preset short-circuit in
sharding_interpreter.py:336-338).  Anything not covered here falls back to
execution discovery, and tests cross-check these rules against discovery.

A rule receives the eqn and returns {"space": ShardSpace, "recombines":
{group: partial}} with rows covering the eqn's tensor (non-Literal-scalar)
inputs in order, or None to decline.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional

from jax.extend import core as jex_core

from easydist_tpu.metashard.annotation import DimSharding, ShardSpace
from easydist_tpu.metashard.combination import Recombine, Reduction
from easydist_tpu.metashard.view_propagation import view_rule

_RULES: Dict[str, Callable] = {}


def register_preset(*prim_names):
    def deco(fn):
        for name in prim_names:
            _RULES[name] = fn
        return fn

    return deco


def preset_rule(eqn, world_size: int) -> Optional[dict]:
    fn = _RULES.get(eqn.primitive.name)
    if fn is None:
        return None
    try:
        return fn(eqn, world_size)
    except Exception:
        return None


def _tensor_avals(eqn) -> List:
    """Avals of the inputs that occupy discovery rows: every non-Literal var
    plus array-valued literals (scalar literals take no row, matching
    MetaOp's jax.Array check)."""
    avals = []
    for v in eqn.invars:
        if isinstance(v, jex_core.Literal):
            if getattr(v.val, "ndim", None) is not None and v.val.ndim > 0:
                avals.append(v.aval)
        else:
            avals.append(v.aval)
    return avals


def _concat(dim):
    return functools.partial(Recombine.concat, dim=dim)


def _reduce(op=Reduction.SUM):
    return functools.partial(Recombine.reduce, op=op)


# ------------------------------------------------------------- elementwise

_ELEMENTWISE = [
    "add", "sub", "mul", "div", "pow", "max", "min", "rem", "atan2",
    "and", "or", "xor", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "nextafter",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "clamp", "add_any",
    "exp", "log", "log1p", "expm1", "tanh", "sin", "cos", "tan", "asin",
    "acos", "atan", "sinh", "cosh", "asinh", "acosh", "atanh", "logistic",
    "sqrt", "rsqrt", "cbrt", "neg", "sign", "abs", "floor", "ceil", "round",
    "is_finite", "not", "erf", "erfc", "erf_inv", "integer_pow", "square",
    "convert_element_type", "stop_gradient", "copy", "real", "imag",
    "exp2", "logb", "population_count", "clz",
]


@register_preset(*_ELEMENTWISE)
def _elementwise_rule(eqn, world_size):
    avals = _tensor_avals(eqn)
    if not avals:
        # all-literal-scalar op: nothing to shard, nothing to execute either
        return {"space": ShardSpace([]), "recombines": {}}
    out_aval = eqn.outvars[0].aval
    rank = out_aval.ndim
    # inputs are same-rank (possibly with broadcasting size-1 dims) or scalar
    for a in avals:
        if a.ndim not in (0, rank):
            return None
        if a.ndim == rank:
            for d in range(rank):
                if a.shape[d] not in (1, out_aval.shape[d]):
                    return None

    table, recombines = [], {}
    dim_groups = {}
    group = 1
    for d in range(rank):
        dim_groups[d] = group
        recombines[group] = _concat(d)
        group += 1
    for a in avals:
        if a.ndim == 0:
            table.append([])
        else:
            # size-1 (broadcast) dims ride along replicated in that group
            table.append([DimSharding(group=dim_groups[d])
                          if a.shape[d] == out_aval.shape[d] != 1
                          else DimSharding()
                          for d in range(rank)])
    # drop groups where no input actually shards (out dim size 1)
    live = {d.group for row in table for d in row if d.group > 0}
    recombines = {g: fn for g, fn in recombines.items() if g in live}
    return {"space": ShardSpace(table), "recombines": recombines}


# -------------------------------------------------------------- dot_general

@register_preset("dot_general")
def _dot_general_rule(eqn, world_size):
    avals = _tensor_avals(eqn)
    if len(avals) != 2:
        return None
    lhs, rhs = avals
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs_row = [DimSharding() for _ in range(lhs.ndim)]
    rhs_row = [DimSharding() for _ in range(rhs.ndim)]
    recombines = {}
    group = 1

    # output layout: batch dims, then lhs free dims, then rhs free dims
    lhs_free = [d for d in range(lhs.ndim) if d not in lc and d not in lb]
    rhs_free = [d for d in range(rhs.ndim) if d not in rc and d not in rb]

    for i, (ld, rd) in enumerate(zip(lb, rb)):
        lhs_row[ld] = DimSharding(group=group)
        rhs_row[rd] = DimSharding(group=group)
        recombines[group] = _concat(i)
        group += 1
    for ld, rd in zip(lc, rc):
        lhs_row[ld] = DimSharding(group=group)
        rhs_row[rd] = DimSharding(group=group)
        recombines[group] = _reduce()
        group += 1
    for i, ld in enumerate(lhs_free):
        lhs_row[ld] = DimSharding(group=group)
        recombines[group] = _concat(len(lb) + i)
        group += 1
    for i, rd in enumerate(rhs_free):
        rhs_row[rd] = DimSharding(group=group)
        recombines[group] = _concat(len(lb) + len(lhs_free) + i)
        group += 1
    return {"space": ShardSpace([lhs_row, rhs_row]), "recombines": recombines}


# ---------------------------------------------------------------- reshape &c

@register_preset("transpose")
def _transpose_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    perm = eqn.params["permutation"]
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    for out_dim, in_dim in enumerate(perm):
        row[in_dim] = DimSharding(group=group)
        recombines[group] = _concat(out_dim)
        group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


@register_preset("broadcast_in_dim")
def _broadcast_rule(eqn, world_size):
    avals = _tensor_avals(eqn)
    if not avals:
        # scalar broadcast: a create-op with no shardable inputs; returning
        # the empty rule (replicate) avoids materializing the (possibly
        # huge) output in eager discovery
        return {"space": ShardSpace([]), "recombines": {}}
    (aval,) = avals
    bcast_dims = eqn.params["broadcast_dimensions"]
    out_shape = eqn.params["shape"]
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    for in_dim, out_dim in enumerate(bcast_dims):
        # size-1 input dims are stretched, not sharded
        if aval.shape[in_dim] == out_shape[out_dim]:
            row[in_dim] = DimSharding(group=group)
            recombines[group] = _concat(out_dim)
            group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


@register_preset("squeeze")
def _squeeze_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    squeezed = set(eqn.params["dimensions"])
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    out_dim = 0
    for d in range(aval.ndim):
        if d in squeezed:
            continue
        row[d] = DimSharding(group=group)
        recombines[group] = _concat(out_dim)
        group += 1
        out_dim += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


@register_preset("reshape")
def _reshape_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    if eqn.params.get("dimensions") is not None:
        return None
    rule = view_rule(list(aval.shape), list(eqn.params["new_sizes"]),
                     world_size=world_size)
    return {"space": rule["space"], "recombines": rule["recombines"]}


# ---------------------------------------------------------------- reductions

_REDUCE_OPS = {
    "reduce_sum": Reduction.SUM,
    "reduce_max": Reduction.MAX,
    "reduce_min": Reduction.MIN,
}


@register_preset("reduce_sum", "reduce_max", "reduce_min")
def _reduce_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    axes = set(eqn.params["axes"])
    red = _REDUCE_OPS[eqn.primitive.name]
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    out_dim = 0
    for d in range(aval.ndim):
        row[d] = DimSharding(group=group)
        if d in axes:
            recombines[group] = _reduce(red)
        else:
            recombines[group] = _concat(out_dim)
            out_dim += 1
        group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


@register_preset("argmax", "argmin", "reduce_and", "reduce_or",
                 "cumsum", "cumlogsumexp", "cumprod", "cummax", "cummin")
def _scan_reduce_rule(eqn, world_size):
    """Only non-reduced/non-scanned dims are shardable."""
    avals = _tensor_avals(eqn)
    if len(avals) != 1:
        return None
    (aval,) = avals
    if "axes" in eqn.params:
        special = set(eqn.params["axes"])
        collapses = True
    else:
        special = {eqn.params["axis"]}
        collapses = False
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    out_dim = 0
    for d in range(aval.ndim):
        if d in special:
            if not collapses:
                out_dim += 1
            continue
        row[d] = DimSharding(group=group)
        recombines[group] = _concat(out_dim)
        group += 1
        out_dim += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


# ------------------------------------------------------------------ slicing

@register_preset("slice")
def _slice_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    starts = eqn.params["start_indices"]
    limits = eqn.params["limit_indices"]
    strides = eqn.params["strides"] or [1] * aval.ndim
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    for d in range(aval.ndim):
        # only dims taken whole can shard
        if starts[d] == 0 and limits[d] == aval.shape[d] and strides[d] == 1:
            row[d] = DimSharding(group=group)
            recombines[group] = _concat(d)
            group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


@register_preset("pad")
def _pad_rule(eqn, world_size):
    avals = _tensor_avals(eqn)
    aval = avals[0]
    config = eqn.params["padding_config"]
    row = [DimSharding() for _ in range(aval.ndim)]
    table = [row] + [[] for _ in avals[1:]]  # padding value is scalar
    recombines = {}
    group = 1
    for d, (lo, hi, interior) in enumerate(config):
        if lo == 0 and hi == 0 and interior == 0:
            row[d] = DimSharding(group=group)
            recombines[group] = _concat(d)
            group += 1
    return {"space": ShardSpace(table), "recombines": recombines}


@register_preset("concatenate")
def _concatenate_rule(eqn, world_size):
    avals = _tensor_avals(eqn)
    cat_dim = eqn.params["dimension"]
    rank = avals[0].ndim
    table = [[DimSharding() for _ in range(rank)] for _ in avals]
    recombines = {}
    group = 1
    for d in range(rank):
        if d == cat_dim:
            continue
        for row in table:
            row[d] = DimSharding(group=group)
        recombines[group] = _concat(d)
        group += 1
    return {"space": ShardSpace(table), "recombines": recombines}


@register_preset("rev")
def _rev_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    flipped = set(eqn.params["dimensions"])
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    for d in range(aval.ndim):
        if d not in flipped:
            row[d] = DimSharding(group=group)
            recombines[group] = _concat(d)
            group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


# -------------------------------------------------------------- convolution

@register_preset("conv_general_dilated")
def _conv_rule(eqn, world_size):
    """Batch and feature-dim rules only; spatial sharding (halo exchange) is
    left to execution discovery or the solver never picks it.  Layouts read
    from dimension_numbers; grouped conv limited to feature_group_count=1
    for the channel rules."""
    avals = _tensor_avals(eqn)
    if len(avals) != 2:
        return None
    lhs, rhs = avals
    dn = eqn.params["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec = dn
    groups_feat = eqn.params.get("feature_group_count", 1)
    batch_count = eqn.params.get("batch_group_count", 1)
    if batch_count != 1:
        return None

    lhs_row = [DimSharding() for _ in range(lhs.ndim)]
    rhs_row = [DimSharding() for _ in range(rhs.ndim)]
    recombines = {}
    group = 1
    # batch: lhs batch dim -> out batch dim
    lhs_row[lhs_spec[0]] = DimSharding(group=group)
    recombines[group] = _concat(out_spec[0])
    group += 1
    if groups_feat == 1:
        # output channels: rhs out-feature dim -> out feature dim
        rhs_row[rhs_spec[0]] = DimSharding(group=group)
        recombines[group] = _concat(out_spec[1])
        group += 1
        # input channels: contraction -> partial
        lhs_row[lhs_spec[1]] = DimSharding(group=group)
        rhs_row[rhs_spec[1]] = DimSharding(group=group)
        recombines[group] = _reduce()
        group += 1
    return {"space": ShardSpace([lhs_row, rhs_row]), "recombines": recombines}


# ------------------------------------------------------- gather / scatter

def _trailing_offset_dims(offset_dims, out_rank):
    return tuple(offset_dims) == tuple(range(out_rank - len(offset_dims),
                                             out_rank))


@register_preset("gather")
def _gather_rule(eqn, world_size):
    """General gather rule (embedding lookup, take_along_axis, batched
    gathers).  GSPMD handles the static slice_sizes under sharding — the
    eager discovery harness cannot, which is why this rule is analytic-only.

    Shardable:
      - indices dims (except the trailing index-vector dim): concat at the
        matching output dim; batching dims also shard the paired operand dim
      - operand slice dims taken WHOLE (slice_sizes[j] == shape[j]): concat
        at the matching offset output dim
    The gathered (start_index_map / collapsed) operand dims never shard."""
    avals = _tensor_avals(eqn)
    if len(avals) != 2:
        return None
    operand, indices = avals
    dn = eqn.params["dimension_numbers"]
    slice_sizes = eqn.params["slice_sizes"]
    out_rank = eqn.outvars[0].aval.ndim

    offset_dims = tuple(dn.offset_dims)
    # output dims not in offset_dims correspond, in order, to indices dims
    # 0..n-2 (the last indices dim is the index vector)
    batch_out_dims = [d for d in range(out_rank) if d not in offset_dims]
    n_idx_batch = indices.ndim - 1
    if len(batch_out_dims) != n_idx_batch:
        return None
    # operand slice dims (not collapsed, not batching) map in order to
    # offset_dims
    slice_dims = [j for j in range(operand.ndim)
                  if j not in dn.collapsed_slice_dims
                  and j not in dn.operand_batching_dims]
    if len(slice_dims) != len(offset_dims):
        return None
    idx_batching = list(dn.start_indices_batching_dims)
    op_batching = list(dn.operand_batching_dims)

    op_row = [DimSharding() for _ in range(operand.ndim)]
    idx_row = [DimSharding() for _ in range(indices.ndim)]
    recombines = {}
    group = 1
    for i in range(n_idx_batch):
        idx_row[i] = DimSharding(group=group)
        if i in idx_batching:
            op_row[op_batching[idx_batching.index(i)]] = DimSharding(group=group)
        recombines[group] = _concat(batch_out_dims[i])
        group += 1
    for k, j in enumerate(slice_dims):
        if slice_sizes[j] == operand.shape[j]:
            op_row[j] = DimSharding(group=group)
            recombines[group] = _concat(offset_dims[k])
            group += 1
    return {"space": ShardSpace([op_row, idx_row]), "recombines": recombines}


@register_preset("scatter-add")
def _scatter_add_rule(eqn, world_size):
    """General scatter-add rule (embedding gradients, take_along_axis
    gradients, batched scatters).

    Shardable:
      - operand window dims (taken whole): shard operand + the matching
        updates window dim, concat at that output dim
      - indices dims: batching dims shard indices+updates+operand together
        (concat); non-batching index dims shard indices+updates and make the
        output PARTIAL(SUM) — scatter-add over index subsets sums exactly."""
    avals = _tensor_avals(eqn)
    if len(avals) != 3:
        return None
    operand, indices, updates = avals
    dn = eqn.params["dimension_numbers"]
    window_dims = tuple(dn.update_window_dims)
    # updates dims not in update_window_dims correspond to indices dims 0..n-2
    upd_batch_dims = [d for d in range(updates.ndim) if d not in window_dims]
    n_idx_batch = indices.ndim - 1
    if len(upd_batch_dims) != n_idx_batch:
        return None
    # operand window dims (not inserted, not batching) map in order to
    # update_window_dims
    op_window = [j for j in range(operand.ndim)
                 if j not in dn.inserted_window_dims
                 and j not in dn.operand_batching_dims]
    if len(op_window) != len(window_dims):
        return None
    idx_batching = list(dn.scatter_indices_batching_dims)
    op_batching = list(dn.operand_batching_dims)

    op_row = [DimSharding() for _ in range(operand.ndim)]
    idx_row = [DimSharding() for _ in range(indices.ndim)]
    upd_row = [DimSharding() for _ in range(updates.ndim)]
    recombines = {}
    group = 1
    for i in range(n_idx_batch):
        idx_row[i] = DimSharding(group=group)
        upd_row[upd_batch_dims[i]] = DimSharding(group=group)
        if i in idx_batching:
            j = op_batching[idx_batching.index(i)]
            op_row[j] = DimSharding(group=group)
            recombines[group] = _concat(j)
        else:
            recombines[group] = _reduce()
        group += 1
    for k, j in enumerate(op_window):
        if updates.shape[window_dims[k]] == operand.shape[j]:
            op_row[j] = DimSharding(group=group)
            upd_row[window_dims[k]] = DimSharding(group=group)
            recombines[group] = _concat(j)
            group += 1
    return {"space": ShardSpace([op_row, idx_row, upd_row]),
            "recombines": recombines}


@register_preset("split")
def _split_rule(eqn, world_size):
    (aval,) = _tensor_avals(eqn)
    axis = eqn.params["axis"]
    n_out = len(eqn.outvars)
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    for d in range(aval.ndim):
        if d == axis:
            continue
        row[d] = DimSharding(group=group)
        recombines[group] = [_concat(d)] * n_out
        group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


# ------------------------------------------------------------ sort / top_k

@register_preset("sort")
def _sort_rule(eqn, world_size):
    """Variadic lax.sort: all operands share one shape; any dim except the
    sort dimension shards freely (the comparator only looks along
    `dimension`), every output concats at the same dim."""
    avals = _tensor_avals(eqn)
    if not avals:
        return None
    shape = avals[0].shape
    if any(a.shape != shape for a in avals):
        return None
    dim = eqn.params["dimension"]
    n_out = len(eqn.outvars)
    rows = [[DimSharding() for _ in shape] for _ in avals]
    recombines = {}
    group = 1
    for d in range(len(shape)):
        if d == dim:
            continue
        for row in rows:
            row[d] = DimSharding(group=group)
        recombines[group] = [_concat(d)] * n_out
        group += 1
    return {"space": ShardSpace(rows), "recombines": recombines}


@register_preset("top_k")
def _top_k_rule(eqn, world_size):
    """lax.top_k selects along the last dim; batch dims shard freely and
    both outputs (values, indices) concat there."""
    (aval,) = _tensor_avals(eqn)
    if aval.ndim == 0:
        return None
    row = [DimSharding() for _ in range(aval.ndim)]
    recombines = {}
    group = 1
    for d in range(aval.ndim - 1):
        row[d] = DimSharding(group=group)
        recombines[group] = [_concat(d)] * len(eqn.outvars)
        group += 1
    return {"space": ShardSpace([row]), "recombines": recombines}


# ------------------------------------------- dynamic slice / dynamic update

@register_preset("dynamic_slice")
def _dynamic_slice_rule(eqn, world_size):
    """Dims taken WHOLE (slice_sizes[d] == shape[d]) shard freely: the
    start index clamps to 0 there, so per-shard slices concat to the
    global slice.  GSPMD handles the baked slice_sizes under sharding —
    the eager harness cannot (full-size param vs shard-size operand),
    which keeps this rule analytic-only (see _CROSSCHECK_SKIP).  Scalar
    start-index operands ride along replicated (empty rows)."""
    avals = _tensor_avals(eqn)
    if not avals or avals[0].ndim == 0:
        return None
    operand, index_avals = avals[0], avals[1:]
    if any(a.ndim != 0 for a in index_avals):
        return None
    slice_sizes = eqn.params["slice_sizes"]
    op_row = [DimSharding() for _ in range(operand.ndim)]
    recombines = {}
    group = 1
    for d in range(operand.ndim):
        if slice_sizes[d] == operand.shape[d]:
            op_row[d] = DimSharding(group=group)
            recombines[group] = _concat(d)
            group += 1
    return {"space": ShardSpace([op_row] + [[] for _ in index_avals]),
            "recombines": recombines}


@register_preset("dynamic_update_slice")
def _dynamic_update_slice_rule(eqn, world_size):
    """Dims where the update covers the WHOLE operand dim shard freely
    (start clamps to 0; operand and update shard together, output concats).
    Analytic-only for the same reason as dynamic_slice."""
    avals = _tensor_avals(eqn)
    if len(avals) < 2 or avals[0].ndim == 0:
        return None
    operand, update, index_avals = avals[0], avals[1], avals[2:]
    if update.ndim != operand.ndim or any(a.ndim != 0 for a in index_avals):
        return None
    op_row = [DimSharding() for _ in range(operand.ndim)]
    upd_row = [DimSharding() for _ in range(update.ndim)]
    recombines = {}
    group = 1
    for d in range(operand.ndim):
        if update.shape[d] == operand.shape[d]:
            op_row[d] = DimSharding(group=group)
            upd_row[d] = DimSharding(group=group)
            recombines[group] = _concat(d)
            group += 1
    return {"space": ShardSpace([op_row, upd_row] +
                                [[] for _ in index_avals]),
            "recombines": recombines}


# --------------------------------------------------------------------- rng

@register_preset("threefry2x32")
def _threefry_rule(eqn, world_size):
    """The threefry2x32 counter hash is elementwise over its broadcast
    (k1, k2, x1, x2) operands: each output element depends only on the
    matching key/counter elements, so counter dims shard freely and both
    output words concat there.  Keys are usually scalar and ride along
    replicated."""
    avals = _tensor_avals(eqn)
    out_aval = eqn.outvars[0].aval
    rank = out_aval.ndim
    if rank == 0:
        return None
    for a in avals:
        if a.ndim not in (0, rank):
            return None
        if a.ndim == rank and any(s not in (1, out_aval.shape[d])
                                  for d, s in enumerate(a.shape)):
            return None
    n_out = len(eqn.outvars)
    table, recombines = [], {}
    group = 1
    dim_groups = {}
    for d in range(rank):
        dim_groups[d] = group
        recombines[group] = [_concat(d)] * n_out
        group += 1
    for a in avals:
        if a.ndim == 0:
            table.append([])
        else:
            table.append([DimSharding(group=dim_groups[d])
                          if a.shape[d] == out_aval.shape[d] != 1
                          else DimSharding()
                          for d in range(rank)])
    live = {d.group for row in table for d in row if d.group > 0}
    recombines = {g: fn for g, fn in recombines.items() if g in live}
    return {"space": ShardSpace(table), "recombines": recombines}


@register_preset("random_bits", "random_wrap", "random_unwrap",
                 "random_seed", "random_fold_in", "random_split")
def _random_rule(eqn, world_size):
    """Typed-key RNG primitives stay replicated: the counter stream is a
    function of flat element position, so a per-shard rebind would
    regenerate the full stream, not a slice of it.  An analytic replicate
    rule skips nshards x candidates of doomed probe executions (and the
    key<fry> avals the eager harness cannot materialize anyway)."""
    avals = _tensor_avals(eqn)
    return {"space": ShardSpace([[DimSharding() for _ in a.shape]
                                 for a in avals]),
            "recombines": {}}


# ------------------------------------------------------------- create ops

@register_preset("iota")
def _create_rule(eqn, world_size):
    """No tensor inputs to shard; output stays replicated (consumers slice
    for free under GSPMD)."""
    return {"space": ShardSpace([]), "recombines": {}}


@register_preset("pallas_call")
def _pallas_call_rule(eqn, world_size):
    """Pallas kernels stay REPLICATED under the auto-solver (for now).

    Execution discovery cannot verify a sharded rebinding — the traced
    eqn's grid_mapping bakes the full-shape grid, so binding shard-sized
    operands is structurally invalid — and GSPMD cannot partition the
    resulting Mosaic custom call either; honoring a SHARD placement would
    need manual shard_map re-emission with a re-traced kernel (ROADMAP).
    Declaring replicate analytically avoids nshards x candidates of doomed
    eager executions and the failed-discovery warning per kernel.
    Multi-device flash attention routes through parallel/ring_attention,
    which composes the kernels per-shard explicitly."""
    avals = _tensor_avals(eqn)
    return {"space": ShardSpace([[DimSharding() for _ in a.shape]
                                 for a in avals]),
            "recombines": {}}


@register_preset("sharding_constraint")
def _sharding_constraint_rule(eqn, world_size):
    """User with_sharding_constraint markers pass through the solver as
    freely shardable identity ops; XLA enforces the user's constraint at
    emission (the scope_auto analog — reference easydist/scope_auto)."""
    (aval,) = _tensor_avals(eqn)
    row = [DimSharding(group=d + 1) for d in range(aval.ndim)]
    recombines = {d + 1: _concat(d) for d in range(aval.ndim)}
    return {"space": ShardSpace([row]), "recombines": recombines}


# ---------------------------------------------------- attention composite

def _attention_strategies(eqn, world_size, backward):
    """Explicit strategy pool for the ed_attention_{fwd,bwd} primitives
    (SURVEY §7 step 7: ring/Ulysses as solver-visible strategies).

    Rows: fwd (q, k, v) / bwd (q, k, v, dout), all [b, h, t, d].
    batch and head sharding are comm-free; seq sharding prices the cheaper
    of ring (ppermute) and Ulysses (all_to_all) as intrinsic cost, with the
    winning variant recorded in strategy meta for emission."""
    import numpy as np

    from easydist_tpu import config as edconfig
    from easydist_tpu.metashard.metair import Placement
    from easydist_tpu.ops.attention_prim import seq_strategy_costs

    q_aval = eqn.invars[0].aval
    b, h, t, d = q_aval.shape
    n_in = 4 if backward else 3
    n_out = 3 if backward else 1
    dtype_bytes = np.dtype(q_aval.dtype).itemsize

    def strat(dim):
        return ([Placement.shard(dim)] * n_in,
                [Placement.shard(dim)] * n_out)

    # MXU-bound compute proxy: 2 matmuls of 2*b*h*t^2*d flops each (the
    # backward does ~2.5x); bytes/hbm under-prices attention by the t/d
    # ratio at long sequence
    flops = 4.0 * b * h * float(t) * t * d * (2.5 if backward else 1.0)
    full_compute = flops / edconfig.peak_flops
    shard_compute = full_compute / world_size

    strategies = []
    if b % world_size == 0:
        ins, outs = strat(0)
        strategies.append((ins, outs, 0.0, shard_compute, None))
    if h % world_size == 0:
        ins, outs = strat(1)
        strategies.append((ins, outs, 0.0, shard_compute, None))
    if t % world_size == 0 and world_size > 1:
        ring, ulysses = seq_strategy_costs((b, h, t, d), dtype_bytes,
                                           world_size, backward)
        # Ulysses needs head divisibility for its head-shard inner compute
        if h % world_size == 0 and ulysses < ring:
            cost, variant = ulysses, "ulysses"
        else:
            cost, variant = ring, "ring"
        ins, outs = strat(2)
        strategies.append((ins, outs, cost, shard_compute,
                           {"variant": variant}))
    if not strategies:
        return None
    return {"space": None, "recombines": {}, "strategies": strategies,
            "compute": full_compute}


@register_preset("ed_attention_fwd")
def _attention_fwd_rule(eqn, world_size):
    return _attention_strategies(eqn, world_size, backward=False)


@register_preset("ed_attention_bwd")
def _attention_bwd_rule(eqn, world_size):
    return _attention_strategies(eqn, world_size, backward=True)
