"""`easydist_compile`: one decorator from an unmodified step function to a
sharded, jitted TPU program.

Pipeline (reference jax/api.py:173-323, redesigned for ND meshes):

  1. trace to jaxpr
  2. ShardingAnalyzer: ShardCombine discovery per unique op signature
  3. per-mesh-axis sequential solve (reference compile_auto.py:128-173):
     bridge -> coarsen (sync-free cone clusters) -> SpmdSolver ILP; shapes
     are pre-shrunk by earlier axes and already-chosen strategies excluded
  4. emit: replay the jaxpr inserting `jax.lax.with_sharding_constraint`
     with the combined ND `PartitionSpec` per tensor, then `jax.jit` with
     sharded `in_shardings` and state buffers donated

XLA's GSPMD partitioner turns the constraints into ICI/DCN collectives —
the TPU equivalent of the reference's sharding_transform + NCCL pass
(torch/passes/sharding.py).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from jax.extend import core as jex_core
from jax.sharding import NamedSharding, PartitionSpec

from easydist_tpu import config as edconfig
from easydist_tpu.autoflow import SpmdSolver
from easydist_tpu.metashard.metair import NodeStrategy, Placement
from .bridge import _eqn_flops, jaxpr_to_metagraph
from .interpreter import ShardingAnalyzer, VarNames
from .mesh import get_axis_specs, get_device_mesh, make_device_mesh

logger = logging.getLogger(__name__)


# ------------------------------------------------------------ state threading

def infer_state_io(args, out_shape) -> Dict[int, int]:
    """Pair output leaves with input leaves for train-state threading.

    Pairing is strictly positional over the *leading* outputs and inputs —
    `(new_params, new_opt, ...) = step(params, opt, ...)` — and stops at the
    first mismatch.  Positional matching (rather than searching all inputs)
    avoids spuriously pairing e.g. an inference output with a data input of
    the same shape, which would wrongly donate the data buffer.
    Returns {flat_output_index: flat_input_index}.
    """
    def leaf_sig(x):
        return (tuple(x.shape), str(x.dtype)) if hasattr(x, "shape") else None

    outs = out_shape if isinstance(out_shape, tuple) else (out_shape,)
    pairs: Dict[int, int] = {}
    in_base = out_base = 0
    for o, a in zip(outs, args):
        o_leaves, o_td = jax.tree_util.tree_flatten(o)
        a_leaves, a_td = jax.tree_util.tree_flatten(a)
        # only container subtrees qualify as state: a bare-array arg is
        # almost always data, and pairing it would donate the data buffer
        # (pass state_io explicitly for single-leaf state)
        if (not o_leaves or o_td != a_td
                or jax.tree_util.treedef_is_leaf(a_td)
                or [leaf_sig(l) for l in o_leaves] != [leaf_sig(l) for l in a_leaves]):
            # warn only when the unpaired output looks like STATE (a
            # container) — a scalar loss ending the pairing is the normal
            # (new_state, loss) shape, not a donation problem
            if pairs and o_leaves and not jax.tree_util.treedef_is_leaf(o_td):
                logger.info(
                    "state_io pairing stopped at output %d (structure "
                    "mismatch): later state will NOT be donated — pass "
                    "state_io explicitly to avoid the extra buffers",
                    out_base)
            break
        for k in range(len(o_leaves)):
            pairs[out_base + k] = in_base + k
        in_base += len(a_leaves)
        out_base += len(o_leaves)
    return pairs


# ------------------------------------------------------------------ emission

def _emit_attention_variant(eqn, strategies, axis_names, mesh, invals):
    """Lower an ed_attention_{fwd,bwd} eqn to the ring/Ulysses program when
    the solver chose a seq-shard strategy (the variant rides the strategy's
    meta, set by the preset rule).  Returns the output list, or None for
    the generic primitive bind (batch/head strategies: GSPMD partitions the
    lowered einsum ops via the constraints already applied)."""
    if eqn.primitive.name not in ("ed_attention_fwd", "ed_attention_bwd"):
        return None
    variant = axis = None
    for ax_name, s in zip(axis_names, strategies):
        meta = getattr(s, "meta", None) if s is not None else None
        if meta and meta.get("variant"):
            variant, axis = meta["variant"], ax_name
            break
    if variant is None:
        return None
    causal = eqn.params["causal"]
    scale = eqn.params["scale"]
    # re-validate the variant for the ACTUAL axis (the rule priced it at
    # the analyzer's min-axis world size): Ulysses needs head divisibility
    # on THIS axis, and the ring/Ulysses crossover moves with axis size
    n_axis = int(mesh.shape[axis])
    heads = eqn.invars[0].aval.shape[1]
    if variant == "ulysses" and heads % n_axis != 0:
        variant = "ring"
    if variant == "ulysses":
        from easydist_tpu.parallel.ulysses import ulysses_attention as attn
    else:
        from easydist_tpu.parallel.ring_attention import ring_attention as attn

    if eqn.primitive.name == "ed_attention_fwd":
        q, k, v = invals
        return [attn(q, k, v, mesh, axis=axis, causal=causal, scale=scale)]
    q, k, v, dout = invals
    # flash-style recompute backward: vjp of the SAME sequence-parallel
    # program — no [t,t] residual, collectives exactly as priced
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attn(q_, k_, v_, mesh, axis=axis, causal=causal,
                                scale=scale), q, k, v)
    return list(vjp(dout))

def _combined_spec(placements: List[Optional[Placement]],
                   axis_names: Sequence[str], ndim: int) -> PartitionSpec:
    """Merge per-axis placements into one PartitionSpec."""
    entries: List[object] = [None] * ndim
    for axis_name, p in zip(axis_names, placements):
        if p is None or not p.is_shard() or p.dim >= ndim:
            continue
        cur = entries[p.dim]
        if cur is None:
            entries[p.dim] = axis_name
        elif isinstance(cur, tuple):
            entries[p.dim] = cur + (axis_name,)
        else:
            entries[p.dim] = (cur, axis_name)
    return PartitionSpec(*entries)


def emit_sharded_fn(closed_jaxpr, names: VarNames,
                    per_axis: List[Dict[str, NodeStrategy]],
                    axis_names: Sequence[str], mesh, remat_plan=None,
                    partial_regions=None):
    """Build fn(*flat_args) -> flat_outs replaying the jaxpr with sharding
    constraints on every strategy-carrying equation input
    (reference add_sharding_jaxpr, jax/api.py:114-170).

    `remat_plan` (schedule/remat.py) redirects planned far consumers to
    recomputed values: before such a consumer, its chain equations are
    re-executed into a shared overlay whose sources pass through
    `optimization_barrier` (so XLA CSE cannot fold the duplicates back),
    and overlay entries are dropped after their last planned reader."""
    jaxpr = closed_jaxpr.jaxpr
    consts = closed_jaxpr.consts
    recompute = remat_plan.recompute if remat_plan else {}
    overlay_last_use = remat_plan.overlay_last_use if remat_plan else {}
    region_at = {}  # start eqn idx -> PartialRegion
    in_region = set()
    for r in (partial_regions or []):
        region_at[r.start] = r
        in_region.update(range(r.start, r.end + 1))

    def sharded_fn(*flat_args):
        from .partial_regions import emit_region

        env = {}
        overlay = {}  # var -> recomputed value (shared across consumers)
        overlay_evict = {}  # eqn idx at which to drop -> [vars]

        def read(v):
            return v.val if isinstance(v, jex_core.Literal) else env[v]

        for var, val in zip(jaxpr.invars, flat_args):
            env[var] = val
        for var, val in zip(jaxpr.constvars, consts):
            env[var] = val

        for idx, eqn in enumerate(jaxpr.eqns):
            if idx in region_at:
                # deferred-reduction region: local chain under shard_map
                # with one psum fence (partial_regions.py)
                emit_region(region_at[idx], jaxpr, env, mesh)
            if idx in in_region:
                continue
            chain = recompute.get(idx)
            if chain:
                for e in chain:
                    ceqn = jaxpr.eqns[e]
                    if all(u in overlay for u in ceqn.outvars):
                        continue
                    csub, cparams = ceqn.primitive.get_bind_params(
                        ceqn.params)
                    cin = []
                    for u in ceqn.invars:
                        if isinstance(u, jex_core.Literal):
                            cin.append(u.val)
                        elif u in overlay:
                            cin.append(overlay[u])
                        else:
                            val = env[u]
                            if hasattr(val, "ndim"):
                                val = jax.lax.optimization_barrier(val)
                            cin.append(val)
                    cout = ceqn.primitive.bind(*csub, *cin, **cparams)
                    if not ceqn.primitive.multiple_results:
                        cout = [cout]
                    last = overlay_last_use.get(e, idx)
                    for u, val in zip(ceqn.outvars, cout):
                        overlay[u] = val
                        overlay_evict.setdefault(last, []).append(u)

            node_name = f"op{idx}"
            strategies = [chosen.get(node_name) for chosen in per_axis]
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            if chain:
                invals = [v.val if isinstance(v, jex_core.Literal)
                          else (overlay[v] if v in overlay else env[v])
                          for v in eqn.invars]
            else:
                invals = [read(v) for v in eqn.invars]

            var_pos = 0
            for i, v in enumerate(eqn.invars):
                if isinstance(v, jex_core.Literal):
                    continue
                placements = [s.in_placements[var_pos]
                              if s is not None and var_pos < len(s.in_placements)
                              else None
                              for s in strategies]
                val = invals[i]
                if hasattr(val, "ndim") and val.ndim > 0 and \
                        any(p is not None and p.is_shard() for p in placements):
                    spec = _combined_spec(placements, axis_names, val.ndim)
                    invals[i] = jax.lax.with_sharding_constraint(
                        val, NamedSharding(mesh, spec))
                var_pos += 1

            out = _emit_attention_variant(eqn, strategies, axis_names, mesh,
                                          invals)
            if out is None:
                out = eqn.primitive.bind(*subfuns, *invals, **bind_params)
                if not eqn.primitive.multiple_results:
                    out = [out]
            for var, val in zip(eqn.outvars, out):
                env[var] = val
            for u in overlay_evict.pop(idx, ()):
                overlay.pop(u, None)

        return [read(v) for v in jaxpr.outvars]

    return sharded_fn


def _compile_cache_key(closed_jaxpr, axis_specs) -> str:
    """Stable key over the traced program + mesh layout (reference compile
    cache, torch/compile_auto.py:97-106)."""
    import hashlib

    from .interpreter import VarNames, eqn_signature, hash_array_bytes

    h = hashlib.sha256()
    # schema + cost-model salt: cached strategies are only valid for the
    # solver/cost-model that produced them; a version bump or a tuned
    # bandwidth/latency knob must miss, not silently serve stale plans
    h.update(("v8|" + "|".join(
        f"{k}={getattr(edconfig, k)}" for k in
        ("ici_bandwidth", "dcn_bandwidth", "ici_latency", "dcn_latency",
         "hbm_bandwidth", "all_to_all_punish_factor",
         "solver_cluster_dedup", "per_device_memory_cap",
         "enable_partial_pools", "enable_auto_remat",
         "coarsen_level", "enable_graph_coarsen", "predict_comm_overlap",
         "comm_overlap_ratio", "allow_repeated_axis_strategy",
         "solver_backend", "liveness_only_input", "peak_flops",
         # comm compression changes reduction-edge prices (cost_model
         # min(exact, compressed)), so cached strategies are mode-specific
         "comm_quant_dtype", "comm_quant_block",
         "comm_quant_min_numel",
         # overlap knobs: the runtime flush/accum shape and the solver's
         # calibrated discount ratio both change the plan's economics
         "comm_overlap", "grad_accum_microbatches",
         "comm_overlap_ratio_source",
         "comm_overlap_ratio_measured",
         # the NaN-step guard rewrites the traced step (lax.cond
         # skip-and-hold around the update), so guarded and unguarded
         # builds must not share cached strategies
         "resilience_step_guard",
         # decode-attention backend/block choice changes the decode-step
         # program (pallas_call kernel vs masked dot_general) at identical
         # input shapes, so serve decode builds must not share strategies
         # across backends
         "decode_attention_backend", "decode_block_k",
         # chunked-prefill backend: same reasoning as the decode backend —
         # different emitted programs at identical shapes
         "prefill_attention_backend"))).encode())
    names = VarNames()
    for v in closed_jaxpr.jaxpr.invars:
        names.name(v)
    for eqn in closed_jaxpr.jaxpr.eqns:
        h.update(eqn_signature(eqn, None).encode())
        # dataflow wiring: two programs with the same op/shape sequence but
        # different operand routing must not collide
        wiring = ",".join(
            "lit" if isinstance(v, jex_core.Literal) else names.name(v)
            for v in eqn.invars)
        wiring += "->" + ",".join(names.name(v) for v in eqn.outvars)
        h.update(wiring.encode())
    for v in closed_jaxpr.jaxpr.invars:
        h.update(f"{v.aval.shape}{v.aval.dtype}".encode())
    for v, c in zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts):
        h.update(f"c{v.aval.shape}{v.aval.dtype}".encode())
        try:
            h.update(hash_array_bytes(np.asarray(c)).encode())
        except Exception:
            pass
    for s in axis_specs:
        h.update(f"{s.name}:{s.size}:{s.kind}".encode())
    return h.hexdigest()[:32]


def _strategy_cache_load(key: str):
    import os
    import pickle

    path = os.path.join(edconfig.compile_cache_dir, f"strategies_{key}.pkl")
    if os.path.exists(path):
        try:
            with open(path, "rb") as f:
                return pickle.load(f)
        except Exception:
            logger.warning("compile cache read failed for %s", path)
    return None


def _strategy_cache_store(key: str, per_axis) -> None:
    import os
    import pickle
    import tempfile

    os.makedirs(edconfig.compile_cache_dir, exist_ok=True)
    path = os.path.join(edconfig.compile_cache_dir, f"strategies_{key}.pkl")
    # write-to-temp + atomic rename: concurrent serve-bucket compiles may
    # read this file mid-write; os.replace guarantees a reader sees either
    # the old pickle or the complete new one, never a torn file
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(dir=edconfig.compile_cache_dir,
                                   prefix=f"strategies_{key}.",
                                   suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            pickle.dump(per_axis, f)
        os.replace(tmp, path)
        tmp = None
    except Exception:
        logger.warning("compile cache write failed for %s", path)
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _dump_strategies(graph, per_axis, axis_names):
    """Write MetaIR + solved strategies into edconfig.dump_dir (reference
    DUMP_STRATEGY/DUMP_CLUSTER flags, config.py and metair.py:933-939)."""
    import os

    os.makedirs(edconfig.dump_dir, exist_ok=True)
    if graph is not None and edconfig.dump_cluster:
        with open(os.path.join(edconfig.dump_dir, "metair.txt"), "w") as f:
            f.write(repr(graph))
        with open(os.path.join(edconfig.dump_dir, "clusters.txt"), "w") as f:
            for c in graph.clusters:
                node_names = [n.name for n in c.nodes.values()]
                f.write(f"cluster {c.cid}: {len(c.strategies)} strategies; "
                        f"nodes {node_names}\n")
    if edconfig.dump_strategy:
        with open(os.path.join(edconfig.dump_dir, "strategies.txt"),
                  "w") as f:
            names = sorted({n for chosen in per_axis for n in chosen})
            for name in names:
                parts = [f"{ax}: {chosen.get(name)}"
                         for ax, chosen in zip(axis_names, per_axis)]
                f.write(f"{name}\n  " + "\n  ".join(parts) + "\n")
    if graph is not None and edconfig.dump_graphviz:
        from easydist_tpu.utils.dump import metagraph_to_dot

        with open(os.path.join(edconfig.dump_dir, "metair.dot"), "w") as f:
            f.write(metagraph_to_dot(graph, per_axis, axis_names))
    logger.info("strategies dumped to %s", edconfig.dump_dir)


# ----------------------------------------------------------------- compiler

class SignatureMismatch(Exception):
    """Raised at trace time when a compiled result sees new shapes/tree."""


class CompileResult:

    def __init__(self, jitted, tree_jitted, in_shardings, strategies, graph,
                 mesh, in_tree, out_tree, n_flat_in, in_avals=None):
        self.jitted = jitted  # flat calling convention (driver/debug use)
        self.tree_jitted = tree_jitted  # pytree convention (steady state)
        self.in_shardings = in_shardings
        self.strategies = strategies  # per-axis {node_name: NodeStrategy}
        self.graph = graph
        self.mesh = mesh
        self.in_tree = in_tree
        self.out_tree = out_tree
        self.n_flat_in = n_flat_in
        self.in_avals = in_avals or []
        self._executable = None
        # layer-1 analyzer findings collected during solve_axes, and the
        # per-axis solver-objective audit records (set by _finish_compile)
        self.analysis_findings: List[object] = []
        self.solver_audits: List[Dict[str, float]] = []
        # state threading declaration {flat out idx -> flat in idx} and
        # the donated flat input indices (set by _finish_compile) — the
        # layer-11 donation/aliasing audit surface
        self.state_pairs: Dict[int, int] = {}
        self.donated_invars: tuple = ()
        self.donated_args: tuple = ()
        # set by _finish_compile for the memory analyzer (layer 3)
        self.closed_jaxpr = None
        self.remat_plan = None
        self.memory_plan = None  # cached MemoryPlan from the last analyze()
        self.predicted_peak_bytes: Optional[int] = None

    def analyze(self, include_program: bool = True,
                include_memory: bool = True):
        """Static analysis of this compiled result (easydist_tpu.analyze):
        the layer-1 strategy findings recorded at solve time, plus, when
        `include_program`, a layer-2 lint of the emitted program (the flat
        sharded function re-traced on abstract values — partial-region
        fences and comm collectives included, no device execution), plus,
        when `include_memory`, the layer-3 memory verifier (graph memory
        plan audit, HBM budget gate, remat-rewrite audit), plus the
        layer-11 donation/aliasing sanitizer (ALIAS001/002 over the
        traced program's donating dispatches, ALIAS002/003 over the
        declared state pairs — the silent-copy and double-claim cases).
        Returns an AnalysisReport; raising is the CALLER's decision
        (CompiledFunction.analyze gates it on `edconfig.analyze_raise`)."""
        from easydist_tpu.analyze import (AnalysisReport,
                                          audit_donation_pairs,
                                          audit_jaxpr_donation,
                                          lint_jaxpr, make_finding)

        report = AnalysisReport(self.analysis_findings)
        traced = None
        if include_program:
            try:
                traced = jax.make_jaxpr(self.jitted)(*self.in_avals)
                axis_sizes = {str(k): int(v)
                              for k, v in self.mesh.shape.items()}
                report.extend(lint_jaxpr(traced.jaxpr, axis_sizes))
            except Exception as e:  # lint must never be the thing that fails
                report.add(make_finding(
                    "COLL000", "emitted-program",
                    f"program lint skipped: retrace failed "
                    f"({type(e).__name__}: {e})"))
            if traced is not None:
                # honorability (ALIAS003) is audited via the state pairs
                # below, where the out<->in context is attached
                report.extend(audit_jaxpr_donation(
                    traced.jaxpr, node="emitted-program",
                    check_unhonored=False))
            report.extend(audit_donation_pairs(self, node="state-io"))
        if include_memory:
            report.extend(self._memory_findings(traced))
        return report

    def _memory_findings(self, traced=None) -> List[object]:
        """Layer 3a: plan this result's graph memory and run the MEM rule
        family over it (easydist_tpu.analyze.memory_rules).  The plan is
        built from the LAST solved axis's (graph, chosen) pair — that
        graph's shapes are already pre-shrunk by every earlier axis, so
        dividing by its own placements yields true per-device bytes."""
        from easydist_tpu.analyze import (audit_remat_plan,
                                          check_hbm_budget, make_finding,
                                          resolve_hbm_budget,
                                          verify_memory_plan)

        if self.graph is None:
            return [make_finding(
                "MEM000", "memory-plan",
                "no MetaGraph on this result (compile-cache hit or "
                "single-device mesh): the memory layer ran — if ever — "
                "on the solving compile")]
        from easydist_tpu.schedule import plan_graph_memory

        findings: List[object] = []
        axis = getattr(self.graph, "solved_axis", None)
        chosen = getattr(self.graph, "solved_chosen", None)
        per_axis = [chosen] if chosen is not None else []
        axis_sizes = [axis.size] if axis is not None else []
        try:
            plan = plan_graph_memory(self.graph, per_axis, axis_sizes)
        except Exception as e:  # analysis must never be the thing that fails
            return [make_finding(
                "MEM000", "memory-plan",
                f"memory planning failed ({type(e).__name__}: {e}); "
                f"MEM rules skipped")]
        self.memory_plan = plan
        self.predicted_peak_bytes = (
            int(self.remat_plan.predicted_peak) if self.remat_plan
            else int(plan.peak_bytes))
        findings.extend(verify_memory_plan(self.graph, plan, per_axis,
                                           axis_sizes))
        budget = resolve_hbm_budget(self.mesh)
        findings.extend(check_hbm_budget(self.graph, plan, budget,
                                         remat_plan=self.remat_plan))
        if self.remat_plan is not None and self.closed_jaxpr is not None:
            findings.extend(audit_remat_plan(self.closed_jaxpr,
                                             self.remat_plan,
                                             traced=traced))
        return findings

    def executable(self):
        """Lower + compile the flat function (cached) — the object carrying
        XLA cost_analysis()/memory_analysis()."""
        if self._executable is None:
            self._executable = self.jitted.lower(*self.in_avals).compile()
            if edconfig.dump_dir and edconfig.dump_hlo:
                import os

                from easydist_tpu.utils.dump import dump_hlo

                os.makedirs(edconfig.dump_dir, exist_ok=True)
                dump_hlo(self._executable,
                         os.path.join(edconfig.dump_dir, "optimized.hlo"))
        return self._executable

    def materialize(self, init_fn, *init_args, arg_offset: int = 0):
        """Deferred sharded materialization (reference init_helper.py:31-166
        materialization strategies; the TPU-native form): run `init_fn`
        under jit with this step's solved input shardings as out_shardings,
        so state is BORN sharded on device — no replicated host copy ever
        exists.  `arg_offset` is the flat input position where init_fn's
        output leaves land in the step's signature (0 = leading state).
        """
        out_shape = jax.eval_shape(init_fn, *init_args)
        leaves = jax.tree_util.tree_leaves(out_shape)
        n = len(leaves)
        expect = self.in_avals[arg_offset:arg_offset + n]
        got = [(tuple(l.shape), np.dtype(l.dtype).name) for l in leaves]
        want = [(tuple(a.shape), np.dtype(a.dtype).name) for a in expect]
        if got != want:
            raise ValueError(
                f"init_fn output does not match the step's inputs at "
                f"arg_offset={arg_offset}: init produces {got[:4]}..., "
                f"step expects {want[:4]}... — wrong offset or init_fn?")
        shardings = self.in_shardings[arg_offset:arg_offset + n]
        tree = jax.tree_util.tree_structure(out_shape)
        out_sh = jax.tree_util.tree_unflatten(tree, shardings)
        return jax.jit(init_fn, out_shardings=out_sh)(*init_args)


def _axis_solve_order(axis_specs):
    """Solve DCN axes first (coarser, costlier), then ICI by size descending
    — the first solve picks the dominant (usually batch) dim."""
    return sorted(range(len(axis_specs)),
                  key=lambda i: (axis_specs[i].kind != "dcn",
                                 -axis_specs[i].size))


def _apply_user_pins(graph, closed_jaxpr, axis):
    """Restrict each `sharding_constraint` node's strategy pool to the
    user's pinned placement on this axis (fix_sharding / user
    with_sharding_constraint).  Without this the solver treats the pin as a
    freely-shardable identity and can choose a conflicting layout that the
    replayed constraint then fights at emission — measured as 2 MiB of
    involuntary-rematerialization all-gathers on a (dp, tp) mesh where the
    solver picked dp-column weight sharding against a tp-row pin."""
    node_by_name = {n.name: n for n in graph.ops}
    for idx, eqn in enumerate(closed_jaxpr.jaxpr.eqns):
        if eqn.primitive.name != "sharding_constraint":
            continue
        spec = getattr(eqn.params.get("sharding"), "spec", None)
        node = node_by_name.get(f"op{idx}")
        if spec is None or node is None or not node.outvars:
            continue
        dim = None
        for d, entry in enumerate(spec):
            entries = entry if isinstance(entry, tuple) else (entry,)
            if axis.name in [e for e in entries if e is not None]:
                dim = d
        if dim is None:
            node.pinned = node.replicate_strategy()
            continue
        shape = node.outvars[0].shape
        if dim >= len(shape) or shape[dim] % axis.size != 0:
            continue  # pin not realizable on this axis; leave solver free
        node.pinned = NodeStrategy([Placement.shard(dim)],
                                   [Placement.shard(dim)])


def solve_axes(closed_jaxpr, axis_specs, world, rules, shape_info, names,
               state_io_names=None, findings=None, audits=None):
    """The per-axis sequential solve (reference compile_auto.py:128-173):
    strategies chosen on earlier axes are excluded from later pools and
    sharded shapes are pre-shrunk, so no dim is double-sharded past
    divisibility.  Shared by compile_step and scoped_region.

    When `findings` is a list and `edconfig.enable_analyze` is on, the
    layer-1 strategy verifier (easydist_tpu.analyze) runs on each axis's
    (graph, chosen) pair right after its solve — the only moment that
    exact pair exists — appending Finding objects; `audits` collects the
    per-axis solver-objective audit records.

    Returns (per_axis strategies list, last metagraph or None)."""
    order = _axis_solve_order(axis_specs)
    per_axis: List[Optional[Dict[str, NodeStrategy]]] = \
        [None] * len(axis_specs)
    var_shapes: Dict[str, Tuple[int, ...]] = {}
    prev_chosen: List[Dict[str, NodeStrategy]] = []
    graph = None
    for axis_idx in order:
        axis = axis_specs[axis_idx]
        if axis.size == 1:
            # single-device axis: every placement is equivalent, skip solving
            per_axis[axis_idx] = {}
            prev_chosen.append({})
            continue
        t0 = time.perf_counter()
        graph = jaxpr_to_metagraph(closed_jaxpr, rules, shape_info,
                                   world_size=world, names=names,
                                   var_shapes=dict(var_shapes),
                                   state_io=state_io_names or {})
        if edconfig.enable_partial_pools:
            # PARTIAL rides linear op chains in the GLOBAL pools: the ILP
            # can then pay a cheaper reduce_scatter fence (P->S) or a
            # single deferred all_reduce instead of one per producer
            # (reference carries partials globally, metair.py:376-481)
            from .interpreter import _inject_partial_propagation

            _inject_partial_propagation(graph, axis.size)
        _apply_user_pins(graph, closed_jaxpr, axis)

        def exclude_map(node, _prev=tuple(prev_chosen)):
            if edconfig.allow_repeated_axis_strategy:
                return []
            out = []
            for chosen in _prev:
                s = chosen.get(node.name)
                if s is not None and not s.is_all_replicate():
                    out.append(s)
            return out

        coarsen_level = (edconfig.coarsen_level
                         if edconfig.enable_graph_coarsen else 0)
        graph.coarsen(axis.size, level=coarsen_level,
                      exclude_map=exclude_map)
        reach = None
        if edconfig.predict_comm_overlap:
            from easydist_tpu.autoflow.reachability import ReachabilityMap

            reach = ReachabilityMap(graph)
        solver = SpmdSolver(graph, axis, reachability=reach)
        chosen = solver.solve()
        # tag the graph with ITS OWN solve pair: later-axis graphs carry
        # shapes pre-shrunk by earlier axes, so the memory analyzer must
        # divide by exactly this one axis's placements (analyze layer 3)
        graph.solved_axis = axis
        graph.solved_chosen = chosen
        if findings is not None and edconfig.enable_analyze:
            from easydist_tpu.analyze import (audit_solver_objective,
                                              verify_axis)

            findings.extend(verify_axis(graph, chosen, axis))
            audit_finding, audit_record = audit_solver_objective(solver,
                                                                 chosen)
            if audit_finding is not None:
                findings.append(audit_finding)
            if audits is not None and "reported" in audit_record:
                audits.append(audit_record)
            if edconfig.predict_comm_overlap:
                from easydist_tpu.analyze import make_finding
                from easydist_tpu.autoflow.cost_model import (
                    overlap_discount_ratio, overlap_ratio_is_measured)

                if (not overlap_ratio_is_measured()
                        and not any(f.rule_id == "OVL003"
                                    for f in findings)):
                    ratio = overlap_discount_ratio()
                    findings.append(make_finding(
                        "OVL003", f"axis:{axis.name}",
                        "predict_comm_overlap is on but no measured "
                        "overlap fraction exists for this backend "
                        f"(source={edconfig.comm_overlap_ratio_source!r} "
                        f"resolves to ratio={ratio:g}"
                        + (", the flat config guess that fails the "
                           "byte-quality gate" if ratio > 0
                           else ", so the discount is inert")
                        + "); run runtime.calibrate.calibrate_overlap() "
                        "on the target to ground the discount"))
        per_axis[axis_idx] = chosen
        prev_chosen.append(chosen)
        logger.info("[solve] axis %s (%d devices) in %.2fs", axis.name,
                    axis.size, time.perf_counter() - t0)

        # shrink shapes sharded on this axis for subsequent solves
        for node in graph.all_nodes():
            strat = chosen.get(node.name)
            if strat is None:
                continue
            for v, p in zip(node.outvars, strat.out_placements):
                if v is not None and p is not None and p.is_shard():
                    shape = list(var_shapes.get(v.name, v.shape))
                    if shape[p.dim] % axis.size == 0:
                        shape[p.dim] //= axis.size
                        var_shapes[v.name] = tuple(shape)
    return per_axis, graph


def compile_step(func, args, kwargs, mesh=None, state_io="auto",
                 donate_state: Optional[bool] = None) -> CompileResult:
    if mesh is None:
        mesh = get_device_mesh()
    if mesh is None:
        mesh = make_device_mesh()
    axis_specs = get_axis_specs(mesh)

    t0 = time.perf_counter()
    from .scope import _compile_mesh_ctx

    with _compile_mesh_ctx(mesh):
        closed_jaxpr, out_shape = jax.make_jaxpr(func, return_shape=True)(
            *args, **kwargs)
    from .inline import inline_calls

    closed_jaxpr = inline_calls(closed_jaxpr)
    jaxpr = closed_jaxpr.jaxpr
    logger.info("[trace] %d eqns in %.2fs", len(jaxpr.eqns),
                time.perf_counter() - t0)

    # measured hardware constants beat datasheet defaults when available
    # (EASYDIST_AUTO_CALIBRATION=0 opts out; run runtime.calibrate() once
    # on the target to record them)
    if edconfig.auto_calibration:
        from easydist_tpu.runtime.calibrate import apply_calibration

        apply_calibration()

    # ---- persistent compile cache: a hit skips discovery AND solving
    cache_key = cached = None
    if edconfig.enable_compile_cache:
        cache_key = _compile_cache_key(closed_jaxpr, axis_specs)
        cached = _strategy_cache_load(cache_key)
        if cached is not None:
            logger.info("[compile cache] hit %s", cache_key)

    # ---- state threading: map output var names to input var names
    flat_args, in_tree = jax.tree_util.tree_flatten((args, kwargs))
    state_pairs: Dict[int, int] = {}
    if state_io == "auto":
        state_pairs = infer_state_io(args, out_shape)
    elif isinstance(state_io, dict):
        state_pairs = state_io
    out_leaves, out_tree = jax.tree_util.tree_flatten(out_shape)

    if cached is not None:
        # names must match the analyzer's assignment order exactly
        names = VarNames()
        for var in jaxpr.invars + jaxpr.constvars:
            names.name(var)
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                names.name(v)
        per_axis = list(cached)
        graph = None
        cache_findings = []
        if edconfig.enable_analyze:
            from easydist_tpu.analyze import make_finding

            cache_findings.append(make_finding(
                "STRAT000", "compile",
                f"compile-cache hit {cache_key}: layer-1 strategy findings "
                f"were produced by the solving compile; only the emitted-"
                f"program lint runs here"))
        return _finish_compile(closed_jaxpr, jaxpr, names, per_axis, graph,
                               axis_specs, mesh, args, kwargs, flat_args,
                               in_tree, out_tree, state_pairs, donate_state,
                               analysis_findings=cache_findings)

    # gate shardability on the SMALLEST axis: per-axis pools re-check
    # divisibility, so a dim only shardable on a small axis must not be
    # filtered out by a larger one
    world = min((s.size for s in axis_specs), default=1)
    analyzer = ShardingAnalyzer(closed_jaxpr, world_size=world)
    rules, shape_info = analyzer.run()  # logs its own one-line summary
    names = analyzer.names
    if edconfig.use_op_cost_db:
        from easydist_tpu.runtime.perfdb import record_discovery

        record_discovery(analyzer.counters.snapshot())

    state_io_names = {}
    for out_idx, in_idx in state_pairs.items():
        if out_idx < len(jaxpr.outvars) and in_idx < len(jaxpr.invars):
            ov = jaxpr.outvars[out_idx]
            if not isinstance(ov, jex_core.Literal):
                state_io_names[names.name(ov)] = names.name(jaxpr.invars[in_idx])

    # ---- per-axis sequential solve (layer-1 analyzer findings collected
    # per axis, on exactly the graph each solve saw)
    # discovery findings (DISC001/DISC002) ride the same report as the
    # solver-layer findings
    analysis_findings: List[object] = list(analyzer.findings)
    solver_audits: List[Dict[str, float]] = []
    per_axis, graph = solve_axes(closed_jaxpr, axis_specs, world, rules,
                                 shape_info, names, state_io_names,
                                 findings=analysis_findings,
                                 audits=solver_audits)

    if edconfig.dump_dir:
        _dump_strategies(graph, [c if c is not None else {} for c in per_axis],
                         [s.name for s in axis_specs])
    if cache_key is not None:
        _strategy_cache_store(cache_key,
                              [c if c is not None else {} for c in per_axis])

    return _finish_compile(closed_jaxpr, jaxpr, names, per_axis, graph,
                           axis_specs, mesh, args, kwargs, flat_args,
                           in_tree, out_tree, state_pairs, donate_state,
                           analysis_findings=analysis_findings,
                           solver_audits=solver_audits)


def _replicated_flops_fraction(jaxpr, per_axis_final, axis_specs) -> float:
    """Fraction of modeled FLOPs in eqns whose chosen strategy is
    all-replicate on every multi-device mesh axis (VERDICT r3 weak #3: the
    silent-zero-parallelism signal)."""
    live_axes = [i for i, s in enumerate(axis_specs) if s.size > 1]
    if not live_axes:
        return 0.0
    total = replicated = 0.0
    for idx, eqn in enumerate(jaxpr.eqns):
        f = _eqn_flops(eqn)
        if f <= 0:
            continue
        total += f
        sharded = False
        for i in live_axes:
            s = per_axis_final[i].get(f"op{idx}")
            if s is not None and any(
                    p is not None and not p.is_replicate()
                    for p in list(s.out_placements) + list(s.in_placements)):
                sharded = True
                break
        if not sharded:
            replicated += f
    return replicated / total if total > 0 else 0.0


# The liveness model is a python-order UPPER bound on XLA's scheduled peak:
# it may exceed what XLA achieves freely, but must never UNDERestimate the
# scheduler's temp bytes by more than this fraction — shared by the remat
# decision here and the bench --analyze planner/XLA drift assertion.
_PEAK_MODEL_UNDER_TOL = 0.05


def peak_model_drift_ok(predicted_bytes, xla_temp_bytes) -> bool:
    """True when the planner's predicted peak respects the upper-bound
    contract vs XLA's own memory_analysis temp bytes.  CPU backends report
    temp_size 0 (same skip as the remat probes above): vacuously OK."""
    if predicted_bytes is None or not xla_temp_bytes or xla_temp_bytes <= 0:
        return True
    return predicted_bytes >= (1.0 - _PEAK_MODEL_UNDER_TOL) * xla_temp_bytes


def _xla_peak_bytes(closed_jaxpr, names, per_axis_final, axis_specs, mesh,
                    remat_plan=None, partial_regions=None):
    """Per-device peak of the sharded program as XLA schedules it: temp +
    argument bytes from memory_analysis (one extra XLA compile; no device
    execution).  Probes the same emission (regions included) that ships."""
    try:
        fn = emit_sharded_fn(closed_jaxpr, names, per_axis_final,
                             [s.name for s in axis_specs], mesh,
                             remat_plan=remat_plan,
                             partial_regions=partial_regions)
        avals = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                 for v in closed_jaxpr.jaxpr.invars]
        ma = jax.jit(fn).lower(*avals).compile().memory_analysis()
        return int(ma.temp_size_in_bytes + ma.argument_size_in_bytes)
    except Exception as e:
        logger.warning("[remat] XLA peak probe failed (%s); trusting the "
                       "liveness model", e)
        return None


def _finish_compile(closed_jaxpr, jaxpr, names, per_axis, graph, axis_specs,
                    mesh, args, kwargs, flat_args, in_tree, out_tree,
                    state_pairs, donate_state, analysis_findings=None,
                    solver_audits=None):
    """Emission + jit from solved strategies (shared by the fresh-solve and
    compile-cache paths)."""
    axis_names = [s.name for s in axis_specs]
    per_axis_final = [c if c is not None else {} for c in per_axis]

    # ---- silent-replication signal: a program whose compute-heavy eqns all
    # chose replicate ships with ZERO parallelism — loudly say so
    replicated_fraction = _replicated_flops_fraction(jaxpr, per_axis_final,
                                                     axis_specs)
    if replicated_fraction > edconfig.replicate_warn_threshold:
        logger.warning(
            "[easydist] %.0f%% of modeled FLOPs run fully REPLICATED on a "
            "%d-device mesh — near-zero parallelism.  Common causes: "
            "indivisible dims, control-flow primitives without sharding "
            "rules, or a cost model preferring replication at these sizes.",
            100.0 * replicated_fraction,
            int(np.prod([s.size for s in axis_specs])))

    # ---- deferred-reduction regions for solver-chosen PARTIAL chains
    # (found BEFORE remat so the memory probes measure the program that
    # actually ships, and remat chains never reach inside a region)
    partial_regions = None
    if edconfig.enable_partial_pools:
        from .partial_regions import find_partial_regions

        partial_regions = find_partial_regions(
            jaxpr, per_axis_final, axis_names,
            [mesh.shape[n] for n in axis_names])
    region_eqns = {i for r in (partial_regions or [])
                   for i in range(r.start, r.end + 1)}

    # ---- memory: plan the per-device peak under the (auto-resolved) HBM
    # cap; over cap -> compiler-chosen remat (schedule/remat.py — the TPU
    # form of the reference memory-opt path, compile_auto.py:353-453)
    remat_plan = None
    if edconfig.enable_auto_remat:
        from easydist_tpu.schedule.remat import plan_remat, resolve_memory_cap

        cap = resolve_memory_cap(mesh)
        if cap > 0:
            state_io_names = {}
            for out_idx, in_idx in state_pairs.items():
                if out_idx < len(jaxpr.outvars) and in_idx < len(jaxpr.invars):
                    ov = jaxpr.outvars[out_idx]
                    if not isinstance(ov, jex_core.Literal):
                        state_io_names[names.name(ov)] = \
                            names.name(jaxpr.invars[in_idx])
            t0 = time.perf_counter()
            axis_sizes = [s.size for s in axis_specs]
            remat_plan = plan_remat(closed_jaxpr, names, per_axis_final,
                                    axis_sizes, cap, state_io_names,
                                    banned_eqns=region_eqns)
            if remat_plan is not None and jax.default_backend() != "cpu":
                # the liveness model is a python-order upper bound; before
                # paying recompute, ask XLA's own scheduler (memory_analysis
                # — ground truth, no execution).  CPU backends report
                # temp_size 0 and skip these checks.
                actual = _xla_peak_bytes(closed_jaxpr, names, per_axis_final,
                                         axis_specs, mesh,
                                         partial_regions=partial_regions)
                if actual is not None and actual <= cap:
                    logger.info(
                        "[remat] model peak %.2f GiB over cap but XLA "
                        "schedules it in %.2f GiB (cap %.2f) — no remat",
                        remat_plan.base_peak / 2**30, actual / 2**30,
                        cap / 2**30)
                    remat_plan = None
                elif actual is not None:
                    # verify the rewrite helps XLA before shipping it:
                    # recompute barriers can also BLOCK scheduler freedom
                    actual_rm = _xla_peak_bytes(
                        closed_jaxpr, names, per_axis_final, axis_specs,
                        mesh, remat_plan=remat_plan,
                        partial_regions=partial_regions)
                    if actual_rm is None or actual_rm >= actual:
                        logger.warning(
                            "[remat] rewrite did not reduce XLA peak "
                            "(%.2f -> %s GiB); dropping it — program "
                            "exceeds the %.2f GiB cap by %.2f GiB",
                            actual / 2**30,
                            actual_rm and f"{actual_rm/2**30:.2f}",
                            cap / 2**30, (actual - cap) / 2**30)
                        remat_plan = None
                    else:
                        logger.info(
                            "[remat] XLA peak %.2f -> %.2f GiB (cap %.2f"
                            " GiB)%s", actual / 2**30, actual_rm / 2**30,
                            cap / 2**30,
                            "" if actual_rm <= cap else " — best effort,"
                            " still over cap")
            if remat_plan:
                logger.info("[remat] planned in %.2fs",
                            time.perf_counter() - t0)

    # ---- input shardings from placeholder strategies
    in_shardings = []
    for i, var in enumerate(jaxpr.invars):
        placements = [c.get(names.name(var)) for c in per_axis_final]
        specs = [s.out_placements[0] if s is not None else None
                 for s in placements]
        ndim = len(var.aval.shape)
        in_shardings.append(NamedSharding(mesh, _combined_spec(
            specs, axis_names, ndim)))

    # ---- emit + jit
    sharded_fn = emit_sharded_fn(closed_jaxpr, names, per_axis_final,
                                 axis_names, mesh, remat_plan=remat_plan,
                                 partial_regions=partial_regions)
    if edconfig.remat_policy != "none":
        # rematerialization policy for callers who differentiate THROUGH the
        # compiled function (a compiled train step already contains its own
        # autodiff and is unaffected): "dots" saves matmul outputs only,
        # "all" recomputes everything
        policies = {"dots": jax.checkpoint_policies.checkpoint_dots,
                    "all": jax.checkpoint_policies.nothing_saveable}
        policy = policies.get(edconfig.remat_policy)
        if policy is None:
            raise ValueError(
                f"unknown remat_policy {edconfig.remat_policy!r}; "
                f"expected none|dots|all")
        sharded_fn = jax.checkpoint(sharded_fn, policy=policy)
    if donate_state is None:
        donate_state = edconfig.enable_donation
    donate = tuple(sorted(set(state_pairs.values()))) if donate_state else ()

    jitted = jax.jit(sharded_fn, in_shardings=in_shardings,
                     donate_argnums=donate)

    # pytree-native variant: flattening/unflattening happens inside the
    # trace, so the per-call path is jax's C++ dispatch (the flat wrapper
    # costs several ms per call at ~250 leaves).  The signature guard runs
    # at TRACE time only: steady-state calls are pure jit cache hits, and a
    # shape/tree change raises SignatureMismatch for the wrapper to catch.
    out_tree_local = out_tree
    expected_tree = in_tree
    expected_avals = [(tuple(v.aval.shape), v.aval.dtype)
                      for v in jaxpr.invars]

    def tree_fn(*t_args, **t_kwargs):
        flat, td = jax.tree_util.tree_flatten((t_args, t_kwargs))
        if td != expected_tree or len(flat) != len(expected_avals) or any(
                tuple(getattr(x, "shape", ())) != s
                or getattr(x, "dtype", None) != d
                for x, (s, d) in zip(flat, expected_avals)):
            raise SignatureMismatch
        # constrain inputs INSIDE the trace rather than pinning jit
        # in_shardings: donated state comes back with XLA-chosen output
        # shardings, and pinned in_shardings would reject it on the next call
        flat = [jax.lax.with_sharding_constraint(x, s)
                if hasattr(x, "ndim") and x.ndim > 0 else x
                for x, s in zip(flat, in_shardings)]
        return jax.tree_util.tree_unflatten(out_tree_local, sharded_fn(*flat))

    # donate the positional args whose leaves are all state (positional
    # prefix pairing guarantees this shape)
    donate_args = []
    if donate:
        donated = set(donate)
        base = 0
        for i, a in enumerate(args):
            n = len(jax.tree_util.tree_leaves(a))
            if n and all(base + k in donated for k in range(n)):
                donate_args.append(i)
            base += n
    tree_jitted = jax.jit(tree_fn, donate_argnums=tuple(donate_args))

    in_avals = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                for v in jaxpr.invars]
    result = CompileResult(jitted, tree_jitted, in_shardings, per_axis_final,
                           graph, mesh, in_tree, out_tree, len(flat_args),
                           in_avals=in_avals)
    result.remat_plan = remat_plan
    result.closed_jaxpr = closed_jaxpr
    # donation audit surface (analyze.audit_decode_donation / SERVE001):
    # flat input indices donated to XLA, and the whole positional args the
    # pytree-native wrapper donates
    result.donated_invars = donate
    result.donated_args = tuple(donate_args)
    # the declared state threading, for the layer-11 donation-pair audit
    # (ALIAS002 double-claimed inputs, ALIAS003 unhonorable pairs)
    result.state_pairs = dict(state_pairs)
    result.replicated_flops_fraction = replicated_fraction
    result.analysis_findings = list(analysis_findings or [])
    result.solver_audits = list(solver_audits or [])
    return result


class CompiledFunction:
    """User-facing wrapper: compiles on first call per input signature and
    replays after (reference CompiledFuncWrapper, jax/api.py:288-304 and
    torch/api.py:53-222)."""

    def __init__(self, func, mesh=None, state_io="auto",
                 donate_state: Optional[bool] = None, compile_only=False):
        self.func = func
        self.mesh = mesh
        self.state_io = state_io
        self.donate_state = donate_state
        self.compile_only = compile_only
        self._cache: Dict[object, CompileResult] = {}
        self._last: Optional[CompileResult] = None
        self._perfdb = None
        self._warmed: set = set()
        self._cache_hits = 0
        self._cache_misses = 0
        functools.update_wrapper(self, func)

    @staticmethod
    def _signature(flat_args, treedef):
        # hashable tuple, no string formatting — this runs on every call
        return (treedef,
                tuple((getattr(l, "shape", ()),
                       getattr(l, "dtype", None) or type(l))
                      for l in flat_args))

    def get_compiled(self, *args, **kwargs) -> CompileResult:
        flat_args, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return self._lookup(flat_args, treedef, args, kwargs)

    # ------------------------------------------------------ stable surface
    # (the serving layer keys its shape-bucketed executable cache on these;
    # keep them additive-only)

    def cache_key(self, *args, **kwargs):
        """Stable hashable key for the compiled-result cache entry these
        args resolve to: (input treedef, per-leaf (shape, dtype)).  Two
        call signatures share an executable iff their keys are equal."""
        flat_args, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return self._signature(flat_args, treedef)

    def compiled_signatures(self):
        """Keys (see `cache_key`) of every signature compiled so far."""
        return tuple(self._cache)

    def cache_stats(self) -> Dict[str, int]:
        """{size, hits, misses} of the compile cache.  Hits count lookups
        that found an existing CompileResult; the `_last` fast path in
        `__call__` bypasses lookup entirely and is not counted."""
        return {"size": len(self._cache), "hits": self._cache_hits,
                "misses": self._cache_misses}

    def executable_for(self, *args, **kwargs):
        """The lowered+compiled XLA executable handle for this signature
        (compiling it first if needed) — the object carrying
        cost_analysis()/memory_analysis()."""
        return self.get_compiled(*args, **kwargs).executable()

    def analyze(self, *args, raise_on_error: Optional[bool] = None,
                include_program: bool = True, include_memory: bool = True,
                export: bool = True, **kwargs):
        """Run the static analyzer (easydist_tpu.analyze) on a compiled
        signature: with args, the signature they resolve to (compiling it
        first if needed); without, the last-called one.

        Exports finding counts to the runtime PerfDB under
        ("analyze_stats", <function name>) and raises AnalysisError on
        error-severity findings unless `raise_on_error=False` or the
        `EASYDIST_ANALYZE_RAISE=0` escape hatch is set.  Returns the
        AnalysisReport."""
        if args or kwargs:
            result = self.get_compiled(*args, **kwargs)
        else:
            result = self._last
            if result is None:
                raise RuntimeError(
                    "analyze(): nothing compiled yet — call the function "
                    "first or pass example args")
        report = result.analyze(include_program=include_program,
                                include_memory=include_memory)
        if export:
            report.export_to_perfdb(
                sub_key=getattr(self.func, "__name__", "step"))
        if raise_on_error is None:
            raise_on_error = edconfig.analyze_raise
        if raise_on_error:
            report.raise_on_errors()
        elif report.errors():
            logger.warning("[analyze] %s", report.summary())
        return report

    def _lookup(self, flat_args, treedef, args, kwargs) -> CompileResult:
        sig = self._signature(flat_args, treedef)
        result = self._cache.get(sig)
        if result is None:
            self._cache_misses += 1
            result = compile_step(
                self.func, args, kwargs, mesh=self.mesh,
                state_io=self.state_io, donate_state=self.donate_state)
            self._cache[sig] = result
        else:
            self._cache_hits += 1
        return result

    def __call__(self, *args, **kwargs):
        if not self.compile_only and self._last is not None:
            # hot path: zero Python beyond jit dispatch; a shape/tree change
            # raises SignatureMismatch during retrace and falls through
            try:
                if edconfig.enable_runtime_prof:
                    return self._profiled_call(args, kwargs)
                return self._last.tree_jitted(*args, **kwargs)
            except SignatureMismatch:
                pass
        flat_args, treedef = jax.tree_util.tree_flatten((args, kwargs))
        result = self._lookup(flat_args, treedef, args, kwargs)
        self._last = result
        if self.compile_only:
            return result
        if edconfig.enable_runtime_prof:
            return self._profiled_call(args, kwargs)
        return result.tree_jitted(*args, **kwargs)

    def _profiled_call(self, args, kwargs):
        """Fenced per-step timing recorded into the persistent PerfDB
        (EASYDIST_RUNTIME_PROF; reference graph_profile_db)."""
        from easydist_tpu.runtime.perfdb import PerfDB

        t0 = time.perf_counter()
        out = self._last.tree_jitted(*args, **kwargs)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if id(self._last) not in self._warmed:
            # first call pays trace + XLA compile; recording it would put a
            # 100-1000x outlier into the persistent step-time history
            self._warmed.add(id(self._last))
            return out
        if self._perfdb is None:
            self._perfdb = PerfDB()
        key = getattr(self.func, "__name__", "step")
        hist = self._perfdb.get_op_perf("step_times", key) or []
        hist = (hist + [dt])[-64:]
        self._perfdb.record_op_perf("step_times", key, hist)
        try:
            self._perfdb.persist()
        except Exception:
            pass
        return out


def easydist_compile(func=None, mesh=None, state_io="auto",
                     donate_state: Optional[bool] = None,
                     compile_only: bool = False,
                     max_solver_time: Optional[float] = None,
                     liveness_only_input: Optional[bool] = None,
                     pp_stages: Optional[int] = None,
                     n_microbatches: Optional[int] = None,
                     pp_axis: str = "pp", schedule: str = "gpipe",
                     lr: Optional[float] = None, optimizer="adam",
                     tp_axes=None):
    """Decorator entrypoint (reference jax/api.py:307-323).

    With `pp_stages=` the decorated function is treated as a LOSS function
    `loss_fn(params, *batch) -> scalar` (mean reduction over the batch) and
    compiled into a hybrid auto-PP x SPMD train step
    (jaxfront/pp_compile.py — the reference's schedule_cls path,
    compile_auto.py:683-715).  The pp path has a different contract (it
    returns a train step with its own optimizer state, not a compiled copy
    of `func`), so the non-pp kwargs `state_io` / `donate_state` /
    `compile_only` are rejected loudly rather than silently ignored;
    `optimizer` accepts "adam", "sgd", or an optax GradientTransformation.
    """
    if max_solver_time is not None:
        edconfig.solver_time_limit = max_solver_time
    if liveness_only_input is not None:
        edconfig.liveness_only_input = liveness_only_input

    def wrap(f):
        if pp_stages is not None:
            from .pp_compile import PPCompiledFunction

            dropped = [name for name, val, default in (
                ("state_io", state_io, "auto"),
                ("donate_state", donate_state, None),
                ("compile_only", compile_only, False)) if val != default]
            if dropped:
                raise ValueError(
                    f"easydist_compile(pp_stages=...) does not support "
                    f"{dropped}: the hybrid path manages its own train "
                    f"state (donated whole) and always compiles lazily on "
                    f"the first init_state call")
            m = mesh or get_device_mesh()
            if m is None:
                raise ValueError("pp_stages= needs an explicit mesh")
            return PPCompiledFunction(
                f, m, pp_stages=pp_stages,
                n_microbatches=n_microbatches or pp_stages * 2,
                pp_axis=pp_axis, schedule=schedule, lr=lr,
                optimizer=optimizer, tp_axes=tp_axes)
        pp_only = [name for name, val, default in (
            ("n_microbatches", n_microbatches, None),
            ("pp_axis", pp_axis, "pp"), ("schedule", schedule, "gpipe"),
            ("lr", lr, None), ("optimizer", optimizer, "adam"),
            ("tp_axes", tp_axes, None))
            if val != default]
        if pp_only:
            raise ValueError(
                f"{pp_only} only apply with pp_stages=; without it the "
                f"decorated function IS the train step (it owns its "
                f"optimizer), so silently dropping them would change "
                f"training behavior")
        return CompiledFunction(f, mesh=mesh, state_io=state_io,
                                donate_state=donate_state,
                                compile_only=compile_only)

    return wrap(func) if func is not None else wrap


def get_opt_strategy(func, *args, mesh=None, **kwargs):
    """Solve and return the per-axis strategy dict without building the
    executable (reference public API: jax/api.py:173 get_opt_strategy)."""
    result = compile_step(func, args, kwargs, mesh=mesh)
    return result.strategies
