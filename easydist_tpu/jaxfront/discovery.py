"""Automap-style pruned ShardCombine discovery (arXiv:2112.02958).

Execution discovery prices every unique ``(primitive, shapes, params)``
signature by running the op ``nshards x candidates`` times — the dominant
compile cost of the whole stack.  Automap's observation is that most of
those signatures are *role-equivalent*: the discovered rule depends on each
dimension's role (which dims are equal, which are broadcast size-1, which
divide the shard count), not on its absolute size.  This module provides
the three pruning substrates the interpreter composes:

  canonical_signature  dim-role-normalized eqn key.  Signatures that agree
                       here form one *propagation group*; discovery runs on
                       the first member and the rule is instantiated for
                       the rest.  Isomorphic subgraphs (stacked transformer
                       layers) collapse because their eqns canonicalize
                       pairwise: layer k's ops hash identically to layer
                       k+1's once var identities are stripped.
  DiscoveryCache       persistent canonical-signature -> rule store (atomic
                       tempfile+os.replace writes, one pickle per knob
                       salt), so warm runs skip probe compilation entirely.
  DiscoveryCounters    probes_compiled / rules_from_group / rules_from_cache
                       / discovery_seconds — exported to the PerfDB and the
                       bench `measured` blocks.

Soundness: a transferred rule is dim-indexed, and the solver re-checks
divisibility against each member's actual shapes at strategy_pool() time,
so role-equivalence only has to guarantee identical discovery *outcomes*.
Rules carrying absolute-size artifacts (halo widths, block-cyclic blocks,
priced composite strategies) transfer only between byte-identical shapes —
`rule_transferable` enforces that, and analyze layer 10 (DISC001) audits
every instantiation after the fact.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import re
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from easydist_tpu import config as edconfig

logger = logging.getLogger(__name__)

# bump to invalidate every persisted discovery rule (schema change, rule
# semantics change); the knob salt handles configuration drift
CACHE_VERSION = "disc-v2"  # v2: positive-uniform float probe inputs

# memory addresses in repr() (bound methods, callables captured in eqn
# params) would make canonical signatures process-unique — strip them so
# the persistent cache can hit across restarts
_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


# --------------------------------------------------------------- counters

class DiscoveryCounters:
    """Per-trace discovery accounting (one instance per top-level
    ShardingAnalyzer; sub-analyzers share their parent's)."""

    _INT_FIELDS = ("probes_compiled", "rules_preset", "rules_from_group",
                   "rules_from_cache", "rules_discovered", "groups",
                   "crosscheck_checked", "crosscheck_failures")

    def __init__(self):
        for f in self._INT_FIELDS:
            setattr(self, f, 0)
        self.discovery_seconds = 0.0

    def snapshot(self) -> Dict[str, float]:
        out = {f: getattr(self, f) for f in self._INT_FIELDS}
        out["discovery_seconds"] = self.discovery_seconds
        return out

    def merge(self, other: "DiscoveryCounters") -> None:
        for f in self._INT_FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        self.discovery_seconds += other.discovery_seconds


# process-wide accumulation across compiles (PerfDB export reads this)
GLOBAL_COUNTERS = DiscoveryCounters()


def reset_global_counters() -> None:
    global GLOBAL_COUNTERS
    GLOBAL_COUNTERS = DiscoveryCounters()


# ----------------------------------------------------- canonical signature

def _has_jaxpr_param(val) -> bool:
    from jax.extend import core as jex_core

    if isinstance(val, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
        return True
    if isinstance(val, (tuple, list)):
        return any(_has_jaxpr_param(v) for v in val)
    return False


def is_composite(eqn) -> bool:
    """Call-like eqns (remat/scan/cond/while/pjit) whose rule embeds a
    priced body solve — canonicalized by exact structure, never merged
    across shapes (the prices are shape-dependent seconds)."""
    return any(_has_jaxpr_param(v) for v in eqn.params.values())


def eqn_tensor_shapes(eqn) -> List[Tuple[int, ...]]:
    """Shapes of the inputs that occupy discovery rows, in row order —
    the same convention presets._tensor_avals / MetaOp use (non-Literal
    vars, plus array-valued literals; scalar literals take no row)."""
    from jax.extend import core as jex_core

    shapes = []
    for v in eqn.invars:
        if isinstance(v, jex_core.Literal):
            if getattr(v.val, "ndim", None) is not None and v.val.ndim > 0:
                shapes.append(tuple(v.val.shape))
        else:
            aval = getattr(v, "aval", None)
            if hasattr(aval, "shape"):
                shapes.append(tuple(aval.shape))
    return shapes


def canonical_signature(eqn, world_size: int) -> str:
    """Dim-role-normalized cache key: two eqns with the same canonical
    signature provably drive execution discovery to the same rule.

    Normalization per tensor dimension:
      - size 1 stays literal (broadcast semantics differ from sharded dims)
      - small sizes stay literal (divisibility/halo edge cases are decided
        by absolute size below ~4x the shard unit)
      - large sizes map to (size-equality class, divisibility flags): the
        class index ties dims that must shrink/shard together (contraction
        partners, residual adds), the flags preserve exactly what the
        discovery harness checks (`% nshards`) and what the solver checks
        downstream (`% world_size`)
    Literal values and params are kept verbatim (address-stripped): any
    shape smuggled through params (reshape new_sizes, slice indices)
    conservatively splits the group.  Composite eqns canonicalize to their
    exact structure hash — body prices are shape-specific.
    """
    from jax.extend import core as jex_core

    from .interpreter import eqn_signature, hash_array_bytes

    prim = eqn.primitive.name
    nshards = edconfig.discovery_nshards

    if is_composite(eqn):
        exact = _ADDR_RE.sub("", eqn_signature(eqn, None))
        digest = hashlib.sha256(exact.encode()).hexdigest()[:24]
        return f"{prim}|w{world_size}|composite:{digest}"

    # sizes at/below the cutoff stay literal: size 1 is broadcast, and a
    # dim the probe harness can't split `nshards` ways twice over has its
    # shardability decided by absolute size.  Above it the (equality-class,
    # divisibility-flags) token preserves exactly what discovery and the
    # solver check, so e.g. dim=256 and ffn=1024 matmuls share one group.
    small_cutoff = max(8, 2 * nshards)
    size_classes: Dict[int, int] = {}

    def tok(size: int) -> str:
        if size <= small_cutoff:
            return str(size)
        cls = size_classes.setdefault(size, len(size_classes))
        # %nshards is what the probe harness checks when splitting a dim;
        # %world_size is what strategy_pool re-checks downstream — the two
        # flags are exactly the size information discovery consumes
        return (f"D{cls}"
                f".{int(size % nshards == 0)}"
                f"{int(size % world_size == 0)}")

    parts = []
    shape_toks: Dict[str, str] = {}  # repr(shape tuple) -> tokenized form
    lit_classes: Dict[str, int] = {}

    def lit_tok(val) -> str:
        """Scalar literals: degenerate values (0, +-1, non-finite) keep
        their value — multiplying by 0 or 1 can collapse probe outputs
        and accidentally match a different recombination — and every
        other value maps to a first-appearance equality class.  The
        VALUE of a generic scalar never feeds the sharding structure,
        only its pattern of reuse across operands does."""
        try:
            f = float(val)
        except (TypeError, ValueError):
            return f"lit:{val!r}"
        if f in (0.0, 1.0, -1.0) or not np.isfinite(f):
            return f"lit:{val!r}"
        cls = lit_classes.setdefault(repr(val), len(lit_classes))
        dt = getattr(val, "dtype", type(val).__name__)
        return f"lit:L{cls}:{dt}"

    def shape_part(shape) -> str:
        dims = ",".join(tok(d) for d in shape)
        if len(shape) >= 1 and any(d > small_cutoff for d in shape):
            shape_toks[repr(tuple(shape))] = f"({dims})"
        return dims

    for v in eqn.invars:
        if isinstance(v, jex_core.Literal):
            val = v.val
            if isinstance(val, np.ndarray) and val.size > 1:
                dims = shape_part(val.shape)
                parts.append(f"lit:{val.dtype.name}[{dims}]:"
                             f"{hash_array_bytes(val)}")
            else:
                parts.append(lit_tok(val))
        else:
            aval = getattr(v, "aval", None)
            if hasattr(aval, "shape"):
                parts.append(f"{aval.dtype.name}[{shape_part(aval.shape)}]")
            else:
                parts.append("?")
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if hasattr(aval, "shape"):
            parts.append(f"->{aval.dtype.name}[{shape_part(aval.shape)}]")
    try:
        params = str(sorted(eqn.params.items()))
    except Exception:
        params = str(eqn.params)
    params = _ADDR_RE.sub("", params)
    # shape-valued params (broadcast_in_dim's shape, full-dim slice limits,
    # ...) co-vary with the tensor shapes: rewrite exact occurrences of an
    # in/out shape tuple with its tokenized form so role-equivalent eqns
    # whose params only restate their shapes still share a group.  Any
    # other param int stays literal and conservatively splits the group.
    for exact, tokd in sorted(shape_toks.items(),
                              key=lambda kv: -len(kv[0])):
        params = params.replace(exact, tokd)
    raw = f"{';'.join(parts)}|{params}"
    digest = hashlib.sha256(raw.encode()).hexdigest()[:24]
    return f"{prim}|w{world_size}|{digest}"


# ------------------------------------------------------- rule transferral

def _space_has_size_artifacts(space) -> bool:
    """True when the discovered space carries absolute-size artifacts
    (halo widths, block-cyclic blocks) that are only valid for the exact
    shapes they were discovered on."""
    for row in space.table:
        for d in row:
            if d.halo is not None or d.block > 1:
                return True
    return False


def rule_transferable(rule: dict, rep_shapes: List[Tuple[int, ...]],
                      eqn) -> bool:
    """Cheap inline soundness gate before serving a representative's rule
    to a group member (analyze layer 10 / DISC001 re-audits afterwards).

    Plain (space-based) rules transfer when row count and ranks line up and
    the space is artifact-free; rules with halos/blocks and priced
    composite strategies transfer only between byte-identical shapes."""
    member_shapes = eqn_tensor_shapes(eqn)
    if rule.get("strategies") is not None:
        return member_shapes == rep_shapes
    space = rule.get("space")
    if space is None:
        return member_shapes == rep_shapes
    if len(member_shapes) != len(rep_shapes):
        return False
    if any(len(m) != len(r) for m, r in zip(member_shapes, rep_shapes)):
        return False
    if len(space.table) != len(member_shapes):
        return False
    if any(len(row) != len(m)
           for row, m in zip(space.table, member_shapes)):
        return False
    if _space_has_size_artifacts(space) and member_shapes != rep_shapes:
        return False
    return True


# ------------------------------------------------------- persistent cache

def cache_salt() -> str:
    """Digest over everything a persisted rule's content depends on: the
    discovery harness knobs, the cost-model knobs (composite rules embed
    priced seconds from body ILP solves), the PerfDB mtime (measured op
    times feed those prices), and the jax version."""
    import jax

    from easydist_tpu.runtime.perfdb import db_mtime

    knobs = (
        # discovery harness
        "discovery_nshards", "extend_space", "allclose_rtol",
        "allclose_atol", "discovery_max_candidates", "discovery_hint_numel",
        "scan_max_seed_solves", "while_trip_estimate",
        # cost model feeding composite body solves
        "ici_bandwidth", "dcn_bandwidth", "ici_latency", "dcn_latency",
        "hbm_bandwidth", "peak_flops", "all_to_all_punish_factor",
        "enable_partial_pools", "solver_backend", "use_op_cost_db",
        "predict_comm_overlap", "comm_overlap_ratio",
        "comm_overlap_ratio_source", "comm_overlap_ratio_measured",
        "comm_quant_dtype", "comm_quant_block", "comm_quant_min_numel",
    )
    parts = [CACHE_VERSION, jax.__version__, str(db_mtime())]
    parts += [f"{k}={getattr(edconfig, k)}" for k in knobs]
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


class DiscoveryCache:
    """Persistent canonical-signature -> rule store.

    One pickle dict per knob salt under the cache dir; loads lazily, writes
    atomically (tempfile + os.replace, the strategy-cache idiom) after
    merging with whatever a concurrent process persisted meanwhile.
    Entries: {"rule": rule_dict, "shapes": row shapes the rule was
    discovered on, "prim": primitive name}."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._mem: Optional[Dict[str, dict]] = None
        self._dirty = False

    def _read_disk(self) -> Dict[str, dict]:
        if os.path.exists(self.path):
            try:
                with open(self.path, "rb") as f:
                    loaded = pickle.load(f)
                if isinstance(loaded, dict):
                    return loaded
            except Exception:
                logger.warning("discovery cache read failed for %s",
                               self.path)
        return {}

    def _load(self) -> None:
        if self._mem is None:
            self._mem = self._read_disk()

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            self._load()
            return self._mem.get(key)

    def put(self, key: str, entry: dict) -> None:
        with self._lock:
            self._load()
            self._mem[key] = entry
            self._dirty = True

    def __len__(self) -> int:
        with self._lock:
            self._load()
            return len(self._mem)

    def flush(self) -> None:
        with self._lock:
            if not self._dirty or self._mem is None:
                return
            merged = self._read_disk()
            merged.update(self._mem)
            tmp = None
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                fd, tmp = tempfile.mkstemp(
                    dir=os.path.dirname(self.path),
                    prefix=os.path.basename(self.path) + ".",
                    suffix=".tmp")
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(merged, f)
                os.replace(tmp, self.path)
                tmp = None
                self._mem = merged
                self._dirty = False
            except Exception:
                # unpicklable entry or unwritable dir: drop persistence for
                # this trace, keep the in-memory rules serving
                logger.warning("discovery cache write failed for %s",
                               self.path)
                if tmp is not None:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass


_caches: Dict[str, DiscoveryCache] = {}
_caches_lock = threading.Lock()


def get_cache() -> Optional[DiscoveryCache]:
    """Resolve the process's DiscoveryCache for the CURRENT knob salt and
    cache dir (tests repoint the dir / flip knobs freely — each distinct
    path gets its own instance).  None when persistence is disabled."""
    if not edconfig.discovery_persistent_cache:
        return None
    base = edconfig.discovery_cache_dir or os.path.join(
        edconfig.compile_cache_dir, "discovery")
    path = os.path.join(base, f"rules_{cache_salt()}.pkl")
    with _caches_lock:
        cache = _caches.get(path)
        if cache is None:
            cache = DiscoveryCache(path)
            _caches[path] = cache
        return cache


def clear_cache_instances() -> None:
    """Drop the in-process DiscoveryCache instances so the next
    get_cache() re-reads its file from disk.  Tests and the --discovery
    bench use this between sweeps to measure a true warm start (disk
    round-trip) instead of hitting the instance's in-memory dict."""
    with _caches_lock:
        _caches.clear()
