"""Device-mesh management for the auto-parallel frontend.

The reference's jax mesh holder is 1D-only (easydist/jax/device_mesh.py:28);
here the mesh is a real `jax.sharding.Mesh` of any rank, with per-axis
interconnect metadata (`MeshAxisSpec`) driving the solver cost model.
Multi-host hybrid meshes put the DCN axis outermost
(`mesh_utils.create_hybrid_device_mesh`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from easydist_tpu.autoflow.cost_model import MeshAxisSpec

_GLOBAL_MESH = None
_GLOBAL_AXIS_SPECS: Optional[List[MeshAxisSpec]] = None


def set_device_mesh(mesh, axis_specs: Optional[Sequence[MeshAxisSpec]] = None):
    """Install `mesh` (jax.sharding.Mesh) as the global mesh.  `axis_specs`
    defaults to all-ICI axes sized from the mesh."""
    global _GLOBAL_MESH, _GLOBAL_AXIS_SPECS
    _GLOBAL_MESH = mesh
    if axis_specs is None:
        axis_specs = [MeshAxisSpec(name=str(name), size=size)
                      for name, size in zip(mesh.axis_names,
                                            mesh.devices.shape)]
    _GLOBAL_AXIS_SPECS = list(axis_specs)


def get_device_mesh():
    return _GLOBAL_MESH


def get_axis_specs(mesh=None) -> List[MeshAxisSpec]:
    """Axis specs for `mesh` — the installed specs when it is the global
    mesh, else default all-ICI specs derived from the mesh itself."""
    if mesh is None or mesh is _GLOBAL_MESH:
        if _GLOBAL_AXIS_SPECS is None:
            raise RuntimeError("device mesh not set; call set_device_mesh or "
                               "pass mesh= to easydist_compile")
        return _GLOBAL_AXIS_SPECS
    return [MeshAxisSpec(name=str(n), size=s)
            for n, s in zip(mesh.axis_names, mesh.devices.shape)]


def make_device_mesh(shape: Optional[Sequence[int]] = None,
                     axis_names: Optional[Sequence[str]] = None,
                     devices=None,
                     dcn_axes: Sequence[str] = ()):
    """Build and install a Mesh.  Default: 1D over all devices.

    `dcn_axes` marks axes that cross slice boundaries so the solver prices
    them at DCN bandwidth.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    if axis_names is None:
        axis_names = tuple(f"mesh{i}" for i in range(len(shape)))
    arr = np.array(devices).reshape(tuple(shape))
    mesh = Mesh(arr, axis_names=tuple(axis_names))
    specs = [MeshAxisSpec(name=str(n), size=s,
                          kind="dcn" if n in dcn_axes else "ici")
             for n, s in zip(axis_names, shape)]
    set_device_mesh(mesh, specs)
    return mesh
