"""JAX frontend: jaxpr tracing -> MetaIR -> solver -> GSPMD emission.

Reference: easydist/jax/ — but where the reference supports only a 1xN mesh
(jax/device_mesh.py:28-29), this frontend solves true ND meshes axis by axis
and lowers to `NamedSharding` over arbitrary ICI/DCN meshes.
"""

from .api import easydist_compile  # noqa: F401
from .mesh import get_device_mesh, set_device_mesh, make_device_mesh  # noqa: F401
from .scope import fix_sharding, scoped_region  # noqa: F401
from .api import get_opt_strategy  # noqa: F401
