"""jaxpr -> MetaGraph bridge (reference: easydist/jax/bridge.py:21-111).

Each jaxpr equation becomes one MetaNode named `op{i}`; every invar/constvar
becomes a placeholder node whose sharding space comes from the analytic view
rule on its own shape (any dim shardable, concat recombination).  Non-Var
(literal) equation inputs are skipped in graph edges but accounted for in the
`arg_rows` mapping so strategy in-placements line up with discovery rows.

The `var_shapes` override lets the frontend pre-shrink shapes already sharded
on previously-solved mesh axes (reference bridge.py:62-83).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
from jax.extend import core as jex_core

from easydist_tpu import config as edconfig
from easydist_tpu.metashard import view_rule
from easydist_tpu.metashard.metair import MetaGraph, MetaNode, MetaVar
from .interpreter import VarNames, eqn_signature


def _eqn_flops(eqn) -> float:
    """Rough FLOP estimate for replication accounting: exact-ish for
    dot_general/conv, length x body for scan, output numel otherwise."""
    import math

    prim = eqn.primitive.name
    if prim == "dot_general":
        (lhs_c, _), (lhs_b, _) = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval
        out = eqn.outvars[0].aval
        k = math.prod(lhs.shape[d] for d in lhs_c) if lhs_c else 1
        return 2.0 * math.prod(out.shape) * k
    if prim in ("conv_general_dilated",):
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        return 2.0 * math.prod(out.shape) * math.prod(rhs.shape[2:]) \
            * rhs.shape[1]
    if prim == "scan":
        inner = eqn.params.get("jaxpr")
        length = eqn.params.get("length", 1)
        if inner is not None and hasattr(inner, "jaxpr"):
            return length * sum(_eqn_flops(e) for e in inner.jaxpr.eqns)
    if prim == "cond":
        branch_flops = [sum(_eqn_flops(e) for e in br.jaxpr.eqns)
                        for br in eqn.params.get("branches", ())
                        if hasattr(br, "jaxpr")]
        if branch_flops:
            return max(branch_flops)
    if prim == "while":
        per_trip = sum(
            _eqn_flops(e)
            for part in (eqn.params.get("body_jaxpr"),
                         eqn.params.get("cond_jaxpr"))
            if part is not None and hasattr(part, "jaxpr")
            for e in part.jaxpr.eqns)
        if per_trip:
            return edconfig.while_trip_estimate * per_trip
    if prim in ("remat2", "remat", "checkpoint", "pjit", "custom_vjp_call",
                "custom_jvp_call"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if inner is not None:
            body = inner.jaxpr if hasattr(inner, "jaxpr") else inner
            return sum(_eqn_flops(e) for e in getattr(body, "eqns", []))
    return float(sum(math.prod(v.aval.shape) for v in eqn.outvars
                     if hasattr(v.aval, "shape")))


def jaxpr_to_metagraph(closed_jaxpr, rules: Dict[str, dict],
                       shape_info: Dict[str, Tuple],
                       world_size: int,
                       names: Optional[VarNames] = None,
                       var_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                       state_io: Optional[Dict[str, str]] = None) -> MetaGraph:
    """Build the MetaGraph.  `state_io` maps output var name -> input var name
    for train-state threading (new params should land where old params live)."""
    jaxpr = closed_jaxpr.jaxpr
    names = names or VarNames()
    var_shapes = var_shapes or {}
    graph = MetaGraph()
    mvars: Dict[str, MetaVar] = {}

    def get_shape(var) -> Tuple[Tuple[int, ...], str]:
        name = names.name(var)
        if name in shape_info:
            shape, dtype = shape_info[name]
        else:
            shape, dtype = tuple(var.aval.shape), var.aval.dtype.name
        return var_shapes.get(name, shape), dtype

    for var in jaxpr.invars + jaxpr.constvars:
        name = names.name(var)
        shape, dtype = get_shape(var)
        mv = MetaVar(name, shape, dtype)
        mvars[name] = mv
        rule = view_rule(list(shape), list(shape), world_size=world_size)
        node = MetaNode(name=name, op_key="placeholder", invars=[],
                        outvars=[mv], space=rule["space"],
                        recombines=rule["recombines"], is_input=True)
        graph.add_input(node)

    for idx, eqn in enumerate(jaxpr.eqns):
        sig = eqn_signature(eqn, names)
        rule = rules.get(sig, {"space": None, "recombines": {}})

        invars, arg_rows = [], []
        row = 0
        for v in eqn.invars:
            if isinstance(v, jex_core.Literal):
                # literal scalars occupy no discovery row and no graph edge
                continue
            invars.append(mvars[names.name(v)])
            arg_rows.append(row)
            row += 1

        outvars = []
        for v in eqn.outvars:
            name = names.name(v)
            shape, dtype = get_shape(v)
            mv = MetaVar(name, shape, dtype)
            mvars[name] = mv
            outvars.append(mv)

        node = MetaNode(name=f"op{idx}", op_key=eqn.primitive.name,
                        invars=invars, outvars=outvars,
                        space=rule["space"], recombines=rule["recombines"],
                        arg_rows=arg_rows, sig=sig)
        if eqn.primitive.name in ("dot_general", "conv_general_dilated"):
            # exact MACs from dimension_numbers, recorded while we still
            # have the eqn: shape-only recovery of the contraction length
            # is ambiguous (square matmuls vs batched dots, r5 review #3)
            node.flops = _eqn_flops(eqn)
        if rule.get("compute") is not None:
            node.compute_proxy = float(rule["compute"])
        if rule.get("strategies") is not None:
            from easydist_tpu.metashard.metair import NodeStrategy

            explicit = []
            for ins, outs, cost, *rest in rule["strategies"]:
                s = NodeStrategy(ins, outs)
                s.intrinsic_cost = float(cost)
                if rest and rest[0] is not None:
                    s.compute_cost = float(rest[0])
                if len(rest) > 1 and rest[1]:
                    # emission metadata (e.g. attention variant ring/ulysses
                    # — same boundary placements, different lowering)
                    s.meta = dict(rest[1])
                explicit.append(s)
            node.explicit_strategies = explicit
        graph.add_op(node)

    for v in jaxpr.outvars:
        if isinstance(v, jex_core.Literal):
            continue
        graph.outputs.append(mvars[names.name(v)])

    if state_io:
        placeholder_by_name = {n.name: n for n in graph.inputs}
        for out_name, in_name in state_io.items():
            if out_name in mvars and in_name in placeholder_by_name:
                graph.state_io[out_name] = placeholder_by_name[in_name]

    return graph
