"""Flatten nested `jit` (pjit) call equations into the outer jaxpr.

jax.nn helpers (log_softmax, gelu, take_along_axis, ...) trace as nested
pjit equations whose inner ops would otherwise be opaque to the preset rule
bank — execution discovery would eagerly run whole subgraphs at full shape
on the host.  Inlining is done by re-tracing an evaluator that recursively
evaluates pjit bodies, letting jax handle all variable bookkeeping.

`remat`/`checkpoint` equations are deliberately NOT inlined: their body must
stay demarcated so XLA preserves rematerialization.
"""

from __future__ import annotations

import jax
from jax.extend import core as jex_core

_INLINE_PRIMS = ("jit", "pjit", "custom_jvp_call", "custom_vjp_call",
                 "custom_vjp_call_jaxpr", "closed_call", "core_call")


def _inner_closed_jaxpr(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        inner = eqn.params.get(key)
        if inner is not None:
            return inner
    return None


def _eval_inline(jaxpr, consts, *args):
    env = {}

    def read(v):
        return v.val if isinstance(v, jex_core.Literal) else env[v]

    for var, val in zip(jaxpr.invars, args):
        env[var] = val
    for var, val in zip(jaxpr.constvars, consts):
        env[var] = val

    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        inner = (_inner_closed_jaxpr(eqn)
                 if eqn.primitive.name in _INLINE_PRIMS else None)
        if inner is not None:
            out = _eval_inline(inner.jaxpr, inner.consts, *invals)
        else:
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            out = eqn.primitive.bind(*subfuns, *invals, **bind_params)
            if not eqn.primitive.multiple_results:
                out = [out]
        for var, val in zip(eqn.outvars, out):
            env[var] = val

    return [read(v) for v in jaxpr.outvars]


def inline_calls(closed_jaxpr):
    """Return a new ClosedJaxpr with nested call prims flattened."""
    if not any(e.primitive.name in _INLINE_PRIMS
               for e in closed_jaxpr.jaxpr.eqns):
        return closed_jaxpr
    avals = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
             for v in closed_jaxpr.jaxpr.invars]

    def flat_fn(*args):
        return _eval_inline(closed_jaxpr.jaxpr, closed_jaxpr.consts, *args)

    return jax.make_jaxpr(flat_fn)(*avals)
