"""Deferred-reduction emission for solver-chosen PARTIAL chains.

GSPMD has no user-visible "partial" annotation: when the solver defers an
all-reduce across a linear chain (dot -> scale -> dot -> sum), plain
constraint emission cannot express it — XLA reduces right after the first
dot (measured: an 8 KiB all-reduce where a 4-byte one suffices).  This pass
finds maximal runs of consecutive equations whose chosen strategy carries
PARTIAL on one mesh axis and emits each run as a `shard_map` region:
sharded sources enter per their solved placement, the chain computes on
local shards (values inside are partial-by-construction), and ONE
`jax.lax.psum` at the region fence realizes the deferred reduction —
exactly the reference's global-partial deferral (metair.py:376-481)
re-expressed with XLA collectives.

v1 scope: single-axis regions (the run's equations must be unsharded on
every other mesh axis), flat primitives only.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

logger = logging.getLogger(__name__)

# primitives that may live inside a region: P-creators (contracted dot /
# sharded-dim reduce) + P-linear chain ops (match the pool injection,
# interpreter._PARTIAL_LINEAR_*)
_REGION_PRIMS = frozenset((
    "dot_general", "reduce_sum", "reshape", "transpose",
    "convert_element_type", "squeeze", "expand_dims", "broadcast_in_dim",
    "neg", "rev", "slice", "copy", "mul", "div", "add_any",
))


@dataclass
class PartialRegion:
    """One consecutive run [start, end] of P-carrying equations."""
    start: int
    end: int
    axis_idx: int
    axis_name: str
    # var -> spec entries for sharded sources ({dim: axis_name})
    source_shard_dim: Dict[object, int] = field(default_factory=dict)
    # region-produced vars read outside the region (fence: psum) mapped to
    # whether they are P (need the psum) at region exit
    fence_partial: Set[object] = field(default_factory=set)
    # fence vars whose every outside consumer wants S(dim): the fence pays
    # psum_scatter (half the wire bytes of the all_reduce) and exits
    # sharded
    fence_scatter: Dict[object, int] = field(default_factory=dict)


def find_partial_regions(jaxpr, per_axis: Sequence[Dict], axis_names,
                         ) -> List[PartialRegion]:
    from jax.extend import core as jex_core

    regions: List[PartialRegion] = []
    n_axes = len(per_axis)
    if n_axes == 0:
        return regions

    def strat(a, idx):
        return per_axis[a].get(f"op{idx}")

    def carries_p(a, idx):
        s = strat(a, idx)
        if s is None:
            return False
        return any(p is not None and p.is_partial()
                   for p in s.out_placements)

    def clean_other_axes(a, idx):
        for b in range(n_axes):
            if b == a:
                continue
            s = strat(b, idx)
            if s is None:
                continue
            if any(p is not None and not p.is_replicate()
                   for p in list(s.out_placements) + list(s.in_placements)):
                return False
        return True

    eqns = jaxpr.eqns
    for a in range(n_axes):
        idx = 0
        while idx < len(eqns):
            if not (carries_p(a, idx)
                    and eqns[idx].primitive.name in _REGION_PRIMS
                    and clean_other_axes(a, idx)):
                idx += 1
                continue
            start = idx
            while idx + 1 < len(eqns) and carries_p(a, idx + 1) \
                    and eqns[idx + 1].primitive.name in _REGION_PRIMS \
                    and clean_other_axes(a, idx + 1):
                idx += 1
            end = idx
            idx += 1
            if end == start:
                # a lone P producer gains nothing from a region; XLA's
                # immediate reduction is already optimal
                continue

            region = PartialRegion(start, end, a, str(axis_names[a]))
            produced: Set[object] = set()
            ok = True
            for j in range(start, end + 1):
                eqn = eqns[j]
                s = strat(a, j)
                pos = 0
                for v in eqn.invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    if v not in produced:
                        p = (s.in_placements[pos]
                             if s and pos < len(s.in_placements) else None)
                        if p is not None and p.is_shard():
                            prev = region.source_shard_dim.get(v)
                            if prev is not None and prev != p.dim:
                                ok = False  # conflicting source shardings
                            region.source_shard_dim[v] = p.dim
                        elif p is not None and p.is_partial() \
                                and v not in produced:
                            ok = False  # P flowing in from outside the run
                    pos += 1
                for v in eqn.outvars:
                    produced.add(v)
            if not ok:
                continue

            # fences: region-produced vars read after the region (or
            # returned); record whether they exit as P
            out_set = {v for v in jaxpr.outvars
                       if not isinstance(v, jex_core.Literal)}
            last_strat = None
            for j in range(start, end + 1):
                p_out = {}
                s = strat(a, j)
                for k, v in enumerate(eqns[j].outvars):
                    p = (s.out_placements[k]
                         if s and k < len(s.out_placements) else None)
                    p_out[v] = p is not None and p.is_partial()
                if last_strat is None:
                    last_strat = {}
                last_strat.update(p_out)
            consumed_later: Set[object] = set()
            consumer_placements: Dict[object, List] = {}
            for j in range(end + 1, len(eqns)):
                s_j = strat(a, j)
                pos = 0
                for v in eqns[j].invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    consumed_later.add(v)
                    if v in produced:
                        p = (s_j.in_placements[pos] if s_j
                             and pos < len(s_j.in_placements) else None)
                        consumer_placements.setdefault(v, []).append(p)
                    pos += 1
            for v in list(produced):
                if v in consumed_later or v in out_set:
                    if last_strat.get(v):
                        region.fence_partial.add(v)
                        ps = consumer_placements.get(v, [])
                        if ps and v not in out_set and all(
                                p is not None and p.is_shard() for p in ps) \
                                and len({p.dim for p in ps}) == 1:
                            region.fence_scatter[v] = ps[0].dim
            regions.append(region)
    # keep non-overlapping regions only (one axis per run; first wins)
    taken: Set[int] = set()
    final = []
    for r in sorted(regions, key=lambda r: (r.start, -(r.end - r.start))):
        span = set(range(r.start, r.end + 1))
        if span & taken:
            continue
        taken |= span
        final.append(r)
    if final:
        logger.info("[partial] %d deferred-reduction region(s): %s",
                    len(final),
                    [(r.start, r.end, r.axis_name) for r in final])
    return final


def emit_region(region: PartialRegion, jaxpr, env, mesh):
    """Execute one region under shard_map: local chain + one psum fence.
    Reads sources from `env`, writes region outputs (post-fence, global
    semantics) back into `env`."""
    import jax
    from jax import shard_map
    from jax.extend import core as jex_core
    from jax.sharding import PartitionSpec

    eqns = jaxpr.eqns[region.start:region.end + 1]
    produced = {v for eqn in eqns for v in eqn.outvars}
    sources = []
    seen = set()
    for eqn in eqns:
        for v in eqn.invars:
            if isinstance(v, jex_core.Literal) or v in produced or v in seen:
                continue
            seen.add(v)
            sources.append(v)
    # region outputs = produced vars needed outside (production order)
    consumed_later: Set[object] = set()
    for e in jaxpr.eqns[region.end + 1:]:
        for v in e.invars:
            if not isinstance(v, jex_core.Literal):
                consumed_later.add(v)
    out_set = {v for v in jaxpr.outvars
               if not isinstance(v, jex_core.Literal)}
    outs = []
    for eqn in eqns:
        for v in eqn.outvars:
            if v in consumed_later or v in out_set:
                outs.append(v)

    axis = region.axis_name
    axis_count = mesh.shape[axis]
    # P->S fence eligibility, decided once (body and out_specs must agree)
    scatter_dim = {}
    for v in outs:
        d = region.fence_scatter.get(v)
        if v in region.fence_partial and d is not None \
                and d < len(v.aval.shape) \
                and v.aval.shape[d] % axis_count == 0:
            scatter_dim[v] = d

    def body(*src_vals):
        local = dict(zip(sources, src_vals))

        def read(v):
            return v.val if isinstance(v, jex_core.Literal) else local[v]

        for eqn in eqns:
            sub, params = eqn.primitive.get_bind_params(eqn.params)
            vals = eqn.primitive.bind(*sub, *[read(v) for v in eqn.invars],
                                      **params)
            if not eqn.primitive.multiple_results:
                vals = [vals]
            for var, val in zip(eqn.outvars, vals):
                local[var] = val
        result = []
        for v in outs:
            val = local[v]
            if v in scatter_dim:
                # P -> S fence: half the wire bytes of the all_reduce,
                # and the consumer wanted the shard anyway
                val = jax.lax.psum_scatter(
                    val, axis, scatter_dimension=scatter_dim[v], tiled=True)
            elif v in region.fence_partial:
                val = jax.lax.psum(val, axis)  # THE deferred reduction
            result.append(val)
        return tuple(result)

    def spec_for(v):
        nd = len(v.aval.shape)
        entries = [None] * nd
        d = region.source_shard_dim.get(v)
        if d is not None and d < nd:
            entries[d] = axis
        return PartitionSpec(*entries)

    def out_spec_for(v):
        d = scatter_dim.get(v)
        if d is None:
            return PartitionSpec()
        entries = [None] * len(v.aval.shape)
        entries[d] = axis
        return PartitionSpec(*entries)

    in_specs = tuple(spec_for(v) for v in sources)
    out_specs = tuple(out_spec_for(v) for v in outs)
    auto = frozenset(mesh.axis_names) - {axis}
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    if auto:
        kwargs["auto"] = auto
    fn = shard_map(body, **kwargs)
    results = fn(*[env[v] for v in sources])
    for v, val in zip(outs, results):
        env[v] = val
