"""Deferred-reduction emission for solver-chosen PARTIAL chains.

GSPMD has no user-visible "partial" annotation: when the solver defers an
all-reduce across a linear chain (dot -> scale -> dot -> sum), plain
constraint emission cannot express it — XLA reduces right after the first
dot (measured: an 8 KiB all-reduce where a 4-byte one suffices).  This pass
finds maximal runs of consecutive equations whose chosen strategy carries
PARTIAL on one mesh axis and emits each run as a `shard_map` region:
sharded sources enter per their solved placement, the chain computes on
local shards (values inside are partial-by-construction), and ONE
`jax.lax.psum` at the region fence realizes the deferred reduction —
exactly the reference's global-partial deferral (metair.py:376-481)
re-expressed with XLA collectives.

Scope: one deferred axis per region, flat primitives only.  Other mesh
axes may carry SHARD placements (hybrid dp x tp): the region is emitted
with EVERY axis manual, using the solved placements as in/out specs, so
GSPMD gets no freedom to re-layout inside (an `auto`-axes variant measured
2 MiB of involuntary-rematerialization all-gathers).  That requires the
run to be sync-free on the other axes — each in-run consumer's placement
must equal its producer's — and excludes runs carrying another axis's
PARTIAL (two simultaneous deferred reductions would need coupled fences).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set

logger = logging.getLogger(__name__)

# primitives that may live inside a region: P-creators (contracted dot /
# sharded-dim reduce) + P-linear chain ops (match the pool injection,
# interpreter._PARTIAL_LINEAR_*)
_REGION_PRIMS = frozenset((
    "dot_general", "reduce_sum", "reshape", "transpose",
    "convert_element_type", "squeeze", "expand_dims", "broadcast_in_dim",
    "neg", "rev", "slice", "copy", "mul", "div", "add_any",
))

# primitives whose params bake in GLOBAL shapes/indices (reshape new_sizes,
# slice start/limit, broadcast target shape) or whose local execution does
# not commute with sharding (rev flips shard order).  Safe when every region
# tensor is full-shape (P on the deferred axis, R elsewhere) — the v1
# situation — but wrong on local blocks, so a run carrying another axis's
# SHARD placements must not contain them.
_GLOBAL_SHAPE_PRIMS = frozenset((
    "reshape", "broadcast_in_dim", "slice", "rev",
))


@dataclass
class PartialRegion:
    """One consecutive run [start, end] of P-carrying equations."""
    start: int
    end: int
    axis_idx: int
    axis_name: str
    # source var -> {tensor dim: axis name} (its solved S placements, the
    # deferred axis AND any other sharded axes)
    source_specs: Dict[object, Dict[int, str]] = field(default_factory=dict)
    # fence var -> {tensor dim: axis name} on the NON-deferred axes (the
    # deferred axis exits replicated, or sharded via fence_scatter)
    out_specs_map: Dict[object, Dict[int, str]] = field(default_factory=dict)
    # region-produced vars read outside the region that are P at region
    # exit (need the psum fence)
    fence_partial: Set[object] = field(default_factory=set)
    # fence vars whose every outside consumer wants S(dim) on the deferred
    # axis: the fence pays psum_scatter (half the all_reduce wire bytes)
    # and exits sharded
    fence_scatter: Dict[object, int] = field(default_factory=dict)


def find_partial_regions(jaxpr, per_axis: Sequence[Dict], axis_names,
                         axis_sizes: Sequence[int]) -> List[PartialRegion]:
    from jax.extend import core as jex_core

    regions: List[PartialRegion] = []
    n_axes = len(per_axis)
    if n_axes == 0:
        return regions

    def strat(a, idx):
        return per_axis[a].get(f"op{idx}")

    def placement_in(a, idx, pos):
        s = strat(a, idx)
        if s is None or pos >= len(s.in_placements):
            return None
        return s.in_placements[pos]

    def placement_out(a, idx, k):
        s = strat(a, idx)
        if s is None or k >= len(s.out_placements):
            return None
        return s.out_placements[k]

    def carries_p(a, idx):
        s = strat(a, idx)
        if s is None:
            return False
        return any(p is not None and p.is_partial()
                   for p in s.out_placements)

    def clean_other_axes(a, idx):
        # other-axis SHARD is fine (emitted manual with the solved specs);
        # other-axis PARTIAL would need a second fence
        for b in range(n_axes):
            if b == a:
                continue
            s = strat(b, idx)
            if s is None:
                continue
            if any(p is not None and p.is_partial()
                   for p in list(s.out_placements) + list(s.in_placements)):
                return False
        return True

    def divisible(v, dim, axis):
        shape = getattr(v.aval, "shape", ())
        return dim < len(shape) and shape[dim] % axis_sizes[axis] == 0

    eqns = jaxpr.eqns
    out_set = {v for v in jaxpr.outvars
               if not isinstance(v, jex_core.Literal)}
    for a in range(n_axes):
        idx = 0
        while idx < len(eqns):
            if not (carries_p(a, idx)
                    and eqns[idx].primitive.name in _REGION_PRIMS
                    and clean_other_axes(a, idx)):
                idx += 1
                continue
            start = idx
            while idx + 1 < len(eqns) and carries_p(a, idx + 1) \
                    and eqns[idx + 1].primitive.name in _REGION_PRIMS \
                    and clean_other_axes(a, idx + 1):
                idx += 1
            end = idx
            idx += 1
            if end == start:
                # a lone P producer gains nothing from a region; XLA's
                # immediate reduction is already optimal
                continue

            region = PartialRegion(start, end, a, str(axis_names[a]))
            produced: Set[object] = set()
            producer_out: Dict[object, Dict[int, object]] = {}
            source_placements: Dict[object, tuple] = {}
            ok = True
            for j in range(start, end + 1):
                eqn = eqns[j]
                pos = 0
                for v in eqn.invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    if v in produced:
                        # sync-free requirement on EVERY axis (including
                        # the deferred one): the consumer must take the
                        # producer's placement as-is.  On axis `a` this
                        # rejects runs where the solver priced a mid-chain
                        # psum (producer P, consumer expecting R/S) — a
                        # region would silently skip that reduction.
                        for b in range(n_axes):
                            pin = placement_in(b, j, pos)
                            pout = producer_out.get(v, {}).get(b)
                            pin_r = pin is None or pin.is_replicate()
                            pout_r = pout is None or pout.is_replicate()
                            if pin_r != pout_r or (
                                    not pin_r and (pin.kind != pout.kind
                                                   or pin.dim != pout.dim)):
                                ok = False
                    else:
                        spec = region.source_specs.setdefault(v, {})
                        # every consuming eqn must read this source with
                        # the SAME per-axis placement: the shard_map slices
                        # the source once, so S-here-R-there (a reshard
                        # edge the solver prices between consumers) cannot
                        # be honored inside one region
                        placements = tuple(placement_in(b, j, pos)
                                           for b in range(n_axes))
                        prev_pl = source_placements.get(v)
                        if prev_pl is None:
                            source_placements[v] = placements
                        elif prev_pl != placements:
                            ok = False
                        for b, p in enumerate(placements):
                            if p is None:
                                continue
                            if p.is_partial():
                                ok = False  # P flowing in from outside
                            elif p.is_shard():
                                prev = spec.get(p.dim)
                                if prev is not None \
                                        and prev != str(axis_names[b]):
                                    ok = False  # two axes on one dim
                                elif not divisible(v, p.dim, b):
                                    ok = False
                                else:
                                    spec[p.dim] = str(axis_names[b])
                        # conflicting sharding of the same source between
                        # two consuming eqns (same axis, different dim)
                        for d1, n1 in list(spec.items()):
                            for d2, n2 in spec.items():
                                if n1 == n2 and d1 != d2:
                                    ok = False
                    pos += 1
                for k, v in enumerate(eqn.outvars):
                    produced.add(v)
                    producer_out[v] = {b: placement_out(b, j, k)
                                       for b in range(n_axes)}
            if not ok:
                continue

            # fences: region-produced vars read after the region (or
            # returned); record whether they exit as P on the deferred axis
            # and their S dims on the other axes
            consumed_later: Set[object] = set()
            consumer_placements: Dict[object, List] = {}
            for j in range(end + 1, len(eqns)):
                pos = 0
                for v in eqns[j].invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    consumed_later.add(v)
                    if v in produced:
                        consumer_placements.setdefault(v, []).append(
                            placement_in(a, j, pos))
                    pos += 1
            for v in list(produced):
                if v not in consumed_later and v not in out_set:
                    continue
                pa = producer_out.get(v, {}).get(a)
                spec = {}
                for b in range(n_axes):
                    if b == a:
                        continue
                    p = producer_out.get(v, {}).get(b)
                    if p is not None and p.is_shard():
                        if not divisible(v, p.dim, b):
                            ok = False
                        spec[p.dim] = str(axis_names[b])
                region.out_specs_map[v] = spec
                if pa is not None and pa.is_partial():
                    region.fence_partial.add(v)
                    ps = consumer_placements.get(v, [])
                    if ps and v not in out_set and all(
                            p is not None and p.is_shard() for p in ps) \
                            and len({p.dim for p in ps}) == 1 \
                            and ps[0].dim not in spec \
                            and divisible(v, ps[0].dim, a):
                        # divisibility decided HERE so the byte gate below
                        # never credits a scatter emit_region would refuse
                        region.fence_scatter[v] = ps[0].dim
            if not ok:
                continue
            # with other-axis SHARD placements anywhere in the run, region
            # tensors are local blocks — global-shape-param prims break
            other_sharded = any(
                b != a and p is not None and p.is_shard()
                for v in produced
                for b, p in producer_out.get(v, {}).items()) or any(
                name != region.axis_name
                for spec in region.source_specs.values()
                for name in spec.values())
            if other_sharded and any(
                    eqns[j].primitive.name in _GLOBAL_SHAPE_PRIMS
                    for j in range(start, end + 1)):
                continue
            # the region must STRICTLY beat immediate reduction: psum-ing
            # every P-creator output (what GSPMD emits with no region)
            # vs psum/psum_scatter at the fence.  A byte-neutral region
            # (e.g. P riding an optimizer update: psum(p - lr*g) costs
            # what psum(g) did) buys nothing and hurts elsewhere — its
            # full-size partials inflate liveness and its eqns are banned
            # from remat chains.
            immediate = 0
            for j in range(start, end + 1):
                s = strat(a, j)
                if s is None:
                    continue
                creates = any(p is not None and p.is_partial()
                              for p in s.out_placements) and not any(
                    p is not None and p.is_partial()
                    for p in s.in_placements)
                if creates:
                    for k, v in enumerate(eqns[j].outvars):
                        p = (s.out_placements[k]
                             if k < len(s.out_placements) else None)
                        if p is not None and p.is_partial():
                            immediate += v.aval.size * v.aval.dtype.itemsize
            fence = sum(
                (v.aval.size * v.aval.dtype.itemsize)
                // (2 if v in region.fence_scatter else 1)
                for v in region.fence_partial)
            if fence >= immediate:
                continue
            regions.append(region)
    # keep non-overlapping regions only (one axis per run; first wins)
    taken: Set[int] = set()
    final = []
    for r in sorted(regions, key=lambda r: (r.start, -(r.end - r.start))):
        span = set(range(r.start, r.end + 1))
        if span & taken:
            continue
        taken |= span
        final.append(r)
    if final:
        logger.info("[partial] %d deferred-reduction region(s): %s",
                    len(final),
                    [(r.start, r.end, r.axis_name) for r in final])
    return final


def emit_region(region: PartialRegion, jaxpr, env, mesh):
    """Execute one region under shard_map: local chain + one psum fence.
    Reads sources from `env`, writes region outputs (post-fence, global
    semantics) back into `env`.  Every mesh axis is manual — in/out specs
    come from the solved placements, so GSPMD cannot re-layout inside."""
    import jax
    from easydist_tpu.utils.jax_compat import shard_map
    from jax.extend import core as jex_core
    from jax.sharding import PartitionSpec

    eqns = jaxpr.eqns[region.start:region.end + 1]
    produced = {v for eqn in eqns for v in eqn.outvars}
    sources = []
    seen = set()
    for eqn in eqns:
        for v in eqn.invars:
            if isinstance(v, jex_core.Literal) or v in produced or v in seen:
                continue
            seen.add(v)
            sources.append(v)
    # region outputs = produced vars needed outside (production order)
    consumed_later: Set[object] = set()
    for e in jaxpr.eqns[region.end + 1:]:
        for v in e.invars:
            if not isinstance(v, jex_core.Literal):
                consumed_later.add(v)
    out_set = {v for v in jaxpr.outvars
               if not isinstance(v, jex_core.Literal)}
    outs = []
    for eqn in eqns:
        for v in eqn.outvars:
            if v in consumed_later or v in out_set:
                outs.append(v)

    axis = region.axis_name
    axis_count = mesh.shape[axis]
    # P->S fence eligibility, decided once (body and out_specs must agree)
    scatter_dim = {}
    for v in outs:
        d = region.fence_scatter.get(v)
        if v in region.fence_partial and d is not None \
                and d < len(v.aval.shape) \
                and v.aval.shape[d] % axis_count == 0:
            scatter_dim[v] = d

    def body(*src_vals):
        local = dict(zip(sources, src_vals))

        def read(v):
            return v.val if isinstance(v, jex_core.Literal) else local[v]

        for eqn in eqns:
            sub, params = eqn.primitive.get_bind_params(eqn.params)
            vals = eqn.primitive.bind(*sub, *[read(v) for v in eqn.invars],
                                      **params)
            if not eqn.primitive.multiple_results:
                vals = [vals]
            for var, val in zip(eqn.outvars, vals):
                local[var] = val
        from easydist_tpu.comm import fence_psum, fence_psum_scatter

        result = []
        for v in outs:
            val = local[v]
            if v in scatter_dim:
                # P -> S fence: half the wire bytes of the all_reduce,
                # and the consumer wanted the shard anyway.  The comm
                # wrapper block-quantizes the wire when enabled and is the
                # exact jax.lax collective when not (docs/COMM.md).
                val = fence_psum_scatter(val, axis, axis_count,
                                         scatter_dim=scatter_dim[v])
            elif v in region.fence_partial:
                # THE deferred reduction
                val = fence_psum(val, axis, axis_count)
            result.append(val)
        return tuple(result)

    def spec_for(v):
        nd = len(v.aval.shape)
        entries = [None] * nd
        for d, name in region.source_specs.get(v, {}).items():
            if d < nd:
                entries[d] = name
        return PartitionSpec(*entries)

    def out_spec_for(v):
        entries = [None] * len(v.aval.shape)
        for d, name in region.out_specs_map.get(v, {}).items():
            if d < len(entries):
                entries[d] = name
        d = scatter_dim.get(v)
        if d is not None:
            entries[d] = axis
        return PartitionSpec(*entries)

    in_specs = tuple(spec_for(v) for v in sources)
    out_specs = tuple(out_spec_for(v) for v in outs)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    results = fn(*[env[v] for v in sources])
    for v, val in zip(outs, results):
        env[v] = val
