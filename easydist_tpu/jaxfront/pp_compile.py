"""One-decorator hybrid auto-PP x SPMD (VERDICT r3 missing #4, r4 weak #1).

`easydist_compile(loss_fn, pp_stages=S, n_microbatches=M, mesh=mesh)` takes
an UNMODIFIED mean-reduction loss function `loss_fn(params, *batch) ->
scalar` and returns a compiled TRAIN STEP over a pp x (anything) mesh:

  1. the loss is traced at sibling-LOCAL microbatch shape (batch divided by
     n_microbatches AND by the product of the non-pp mesh axis sizes) and
     auto-split into S FLOP-balanced stages
     (`parallel/auto_pipeline._StagePlan`; user `split_point` markers
     honored)
  2. stage-exclusive params are packed per stage, sharded over the pp axis
     AND (flat, ZeRO-style) over every sibling axis — per-device param
     bytes ~ total / n_devices
  3. the pipeline runs as ONE fully-manual shard_map over every mesh axis:
     sibling axes batch-parallelise each stage (each sibling lane pipelines
     its own batch shard), packed rows are all-gathered at one uniform
     point per step, and the loss is sibling-averaged after the scan.
     Nothing inside the divergent `lax.switch` stage branches communicates
     — the partial-auto design this replaces deadlocked because GSPMD
     inserted resharding collectives inside branches that different pp
     groups never jointly reach (VERDICT r4 weak #1, judge probe)
  4. jax autodiff through the ppermute pipeline yields the backward
     schedule; the optimizer (traced Adam/SGD from models/optim.py, or any
     optax GradientTransformation) runs elementwise on the packed
     representation, so optimizer state is sharded exactly like the params

Reference equivalent: passing `schedule_cls` to the same compile entry
(easydist/torch/compile_auto.py:683-715) — there the stages are per-rank
processes with DTensor-sharded submodules over NCCL; here one fully-manual
SPMD program over ICI.

Schedules: "gpipe" (fill-drain + autodiff backward), "remat" (gpipe with
per-stage rematerialization) and "1f1b" (DAPPLE supertick on the
heterogeneous switch branches, `parallel/auto_pipeline.
pipeline_1f1b_grad` — O(n_stages) residual memory instead of gpipe's
O(n_microbatches), gradients computed in-schedule).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec



def _struct(tree):
    """Shape/dtype signature used to pin the build geometry."""
    return jax.tree_util.tree_map(
        lambda x: (tuple(x.shape), jnp.result_type(x)), tree)


class PPCompiledFunction:
    """Hybrid-compiled train step.  Usage:

        compiled = easydist_compile(loss_fn, pp_stages=4,
                                    n_microbatches=8, mesh=mesh)
        state = compiled.init_state(params, *batch)   # packs + shards
        state, loss = compiled(state, *batch)         # one train step
    """

    def __init__(self, loss_fn: Callable, mesh, pp_stages: int,
                 n_microbatches: int, pp_axis: str = "pp",
                 schedule: str = "gpipe", lr: Optional[float] = None,
                 optimizer="adam", tp_axes=None):
        if schedule not in ("gpipe", "remat", "1f1b"):
            raise NotImplementedError(
                f"unknown schedule {schedule!r}; auto-split supports "
                f"'gpipe', 'remat' (gpipe + per-stage rematerialization) "
                f"and '1f1b' (DAPPLE supertick, O(n_stages) residual "
                f"memory)")
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.pp_stages = pp_stages
        self.n_microbatches = n_microbatches
        self.pp_axis = pp_axis
        self.schedule = schedule
        is_optax = hasattr(optimizer, "init") and hasattr(optimizer, "update")
        if not is_optax and optimizer not in ("adam", "sgd"):
            raise ValueError(
                f"optimizer must be 'adam', 'sgd', or an optax "
                f"GradientTransformation, got {optimizer!r}")
        if is_optax and lr is not None:
            raise ValueError(
                "lr= is ignored with an optax optimizer — set the learning "
                "rate inside the GradientTransformation instead")
        self.lr = 1e-4 if lr is None else lr
        self.optimizer = optimizer
        tp_axes = tuple(tp_axes or ())
        if len(tp_axes) > 1:
            raise NotImplementedError(
                "one tp axis per hybrid compile for now")
        for name in tp_axes:
            if name == pp_axis or name not in mesh.axis_names:
                raise ValueError(
                    f"tp axis {name!r} must be a non-pp mesh axis "
                    f"(mesh has {mesh.axis_names})")
        self.tp_axes = tp_axes
        self._tp_plan = None  # filled by _build when tp_axes is set
        self._is_optax = is_optax
        self._built = None  # (jitted step, init_state, pack_params)
        self._batch_struct = None  # pytree/shape signature the build traced

    # ------------------------------------------------------------- build

    def _build(self, params, batch):
        from easydist_tpu.models.optim import (adam_init, adam_update,
                                               sgd_update)
        from easydist_tpu.parallel.auto_pipeline import pipeline_forward

        M = self.n_microbatches
        mesh, pp_axis = self.mesh, self.pp_axis
        if pp_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no {pp_axis!r} axis: "
                             f"{mesh.axis_names}")
        if mesh.shape[pp_axis] != self.pp_stages:
            raise ValueError(
                f"mesh axis {pp_axis!r} has size {mesh.shape[pp_axis]}, "
                f"expected pp_stages={self.pp_stages}")
        sib_axes = tuple(n for n in mesh.axis_names if n != pp_axis)

        # non-float param leaves (bool masks, int tables — e.g. HF GPT-2's
        # causal-mask buffers) cannot ride the float transport or the adam
        # update: bake them into the traced closure as constants and
        # pipeline only the differentiable leaves
        all_leaves, pdef = jax.tree_util.tree_flatten(params)
        diff_idx = [i for i, l in enumerate(all_leaves)
                    if jnp.issubdtype(jnp.result_type(l), jnp.inexact)]
        const_vals = {i: l for i, l in enumerate(all_leaves)
                      if i not in set(diff_idx)}
        self._diff_idx, self._params_treedef = diff_idx, pdef
        self._const_baked = {i: l for i, l in const_vals.items()}

        def merge(diff_leaves):
            out = list(const_vals.get(i) for i in range(len(all_leaves)))
            for i, l in zip(diff_idx, diff_leaves):
                out[i] = l
            return jax.tree_util.tree_unflatten(pdef, out)

        diff_example = [all_leaves[i] for i in diff_idx]

        def loss_flat_mb(p, mb_tuple):
            return self.loss_fn(merge(p), *mb_tuple)

        from easydist_tpu.jaxfront.inline import inline_calls

        def batch_division(tp_axes):
            """(to_mb, mb_local, closed) for a given tp-axis choice: the
            non-tp siblings divide the batch; tp axes see it whole.  One
            trace serves the tp solve AND the pipeline builders, so eqn
            indices in tp_plan reference THIS jaxpr, not a re-trace."""
            batch_axes = tuple(n for n in sib_axes if n not in tp_axes)
            n_batch = math.prod(mesh.shape[n] for n in batch_axes)

            def to_mb(x):
                if x.shape[0] % (M * n_batch) != 0:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"n_microbatches*batch-siblings = {M}*{n_batch}")
                return x.reshape((M, x.shape[0] // M) + x.shape[1:])

            def to_local_mb(x):
                mb = to_mb(x)[0]
                return mb[: mb.shape[0] // n_batch]

            mb_local = tuple(jax.tree_util.tree_map(to_local_mb, b)
                             for b in batch)
            closed = inline_calls(jax.make_jaxpr(loss_flat_mb)(
                diff_example, mb_local))
            return to_mb, mb_local, closed

        to_mb, mb_local, closed = batch_division(self.tp_axes)
        tp_plan = tp_axis = None
        if self.tp_axes:
            tp_axis = self.tp_axes[0]
            tp_plan = self._solve_tp(closed, tp_axis, mesh.shape[tp_axis])
            self._tp_plan = tp_plan
            if not tp_plan:
                # Nothing profitable to tensor-shard: the tp axis runs
                # IDLE (replicated compute; gradients lane-averaged by the
                # mean-class machinery) rather than re-tracing with tp as
                # a batch axis — a torch-exported loss has concrete view
                # shapes baked in and cannot re-trace at a different local
                # batch (r5 review #2).  Warn: dropping tp_axes (or adding
                # it to the batch axes) is strictly more efficient.
                import logging

                logging.getLogger(__name__).warning(
                    "[pp-hybrid] tp solver found nothing profitable to "
                    "shard; axis %r runs idle — drop tp_axes= for batch "
                    "parallelism instead", tp_axis)

        if self.schedule == "1f1b":
            from easydist_tpu.parallel.auto_pipeline import (
                pipeline_1f1b_grad)

            pipe_grad, pack_params = pipeline_1f1b_grad(
                loss_flat_mb, diff_example, mb_local, mesh,
                n_stages=self.pp_stages, n_microbatches=M, axis=pp_axis,
                tp_plan=tp_plan, tp_axis=tp_axis, closed=closed)
            pipe = None
        else:
            pipe, pack_params = pipeline_forward(
                loss_flat_mb, diff_example, mb_local, mesh,
                n_stages=self.pp_stages, n_microbatches=M, axis=pp_axis,
                shard_params=True, manual_siblings=True,
                remat_stages=(self.schedule == "remat"),
                tp_plan=tp_plan, tp_axis=tp_axis, closed=closed)
            pipe_grad = None

        # storage shardings: packed stage rows split over pp AND, flat,
        # over every sibling axis (params/device ~ total/n_devices); this
        # matches the shard_map in_specs exactly, so dispatch moves nothing
        packed_sharding = NamedSharding(
            mesh, PartitionSpec(pp_axis, sib_axes or None))

        if self._is_optax:
            opt_init, opt_update = self.optimizer.init, self.optimizer.update
        else:
            opt_init = adam_init if self.optimizer == "adam" else None
            opt_update = (adam_update if self.optimizer == "adam"
                          else sgd_update)

        def step(state, *batch_args):
            params_repr, opt = state
            mbs = tuple(jax.tree_util.tree_map(to_mb, b)
                        for b in batch_args)

            if pipe_grad is not None:  # 1f1b computes grads in-schedule
                loss, grads = pipe_grad(params_repr, mbs)
            else:
                def loss_of(pr):
                    losses = pipe(pr, mbs)  # [M] sibling-averaged scalars
                    return jnp.mean(losses)

                loss, grads = jax.value_and_grad(loss_of)(params_repr)
            if self._is_optax:
                updates, new_opt = opt_update(grads, opt, params_repr)
                new_repr = jax.tree_util.tree_map(
                    lambda p, u: p + u, params_repr, updates)
            elif self.optimizer == "adam":
                new_repr, new_opt = opt_update(params_repr, grads, opt,
                                               lr=self.lr)
            else:
                new_repr = opt_update(params_repr, grads, lr=self.lr)
                new_opt = opt
            return (new_repr, new_opt), loss

        jitted = jax.jit(step, donate_argnums=(0,))

        def init_state(raw_params):
            raw_leaves = jax.tree_util.tree_leaves(raw_params)
            repr_ = pack_params([raw_leaves[i] for i in diff_idx])
            packed, shared = repr_
            placed = (jax.device_put(packed, packed_sharding), shared)
            opt = opt_init(placed) if opt_init is not None else ()
            return (placed, opt)

        self._built = (jitted, init_state, pack_params)
        self._batch_struct = _struct(batch)
        return self._built

    # --------------------------------------------------------- introspection

    @property
    def tp_plan(self):
        """Read-only copy of the solver's tensor-parallel plan:
        {eqn index: NodeStrategy} over the traced loss jaxpr (empty when
        tp_axes was not given, nothing was profitable, or before the
        first init_state builds)."""
        return dict(self._tp_plan) if self._tp_plan else {}

    def tp_summary(self):
        """{'planned': total strategies, 'sharded': strategies that shard
        at least one operand} — the stable way to report what the tp
        solver decided (examples/jax/hybrid_pp_tp.py)."""
        plan = self.tp_plan
        sharded = sum(
            1 for s in plan.values()
            if any(q is not None and q.is_shard()
                   for q in list(s.in_placements) + list(s.out_placements)))
        return {"planned": len(plan), "sharded": sharded}

    # ------------------------------------------------------------ tp solve

    # composite / specially-lowered primitives: their solver strategies
    # describe whole-body assignments (internal collectives included) that
    # a raw primitive re-bind with sliced operands cannot honor — the
    # branch replay keeps them replicated over tp instead
    _TP_REPLAY_SKIP = frozenset({
        "scan", "while", "cond", "remat2", "remat", "checkpoint",
        "ed_attention_fwd", "ed_attention_bwd"})

    def _solve_tp(self, closed, tp_axis: str, tp_size: int):
        """Per-eqn tensor-parallel plan for the tp axis: run discovery +
        the per-axis ILP on the (batch-local) loss jaxpr at the tp axis's
        own size (fixes VERDICT r4 weak #6 — the old path solved at
        world=min(sibling sizes)).  The returned {eqn idx: NodeStrategy}
        drives the placement-tracked branch replay
        (parallel/auto_pipeline._tp_convert) with explicit manual
        collectives; the SAME `closed` jaxpr feeds the pipeline builders,
        so eqn indices align by construction."""
        from easydist_tpu.autoflow import MeshAxisSpec

        from .api import solve_axes
        from .interpreter import ShardingAnalyzer

        analyzer = ShardingAnalyzer(closed, world_size=tp_size)
        rules, shape_info = analyzer.run()
        spec = MeshAxisSpec(tp_axis, tp_size)
        per_axis, _ = solve_axes(closed, [spec], tp_size, rules,
                                 shape_info, analyzer.names)
        chosen = per_axis[0] or {}
        tp_plan = {}
        for idx, eqn in enumerate(closed.jaxpr.eqns):
            if eqn.primitive.name in self._TP_REPLAY_SKIP:
                continue
            s = chosen.get(f"op{idx}")
            if s is None or s.is_all_replicate():
                continue
            if getattr(s, "compute_cost", None) is not None \
                    or getattr(s, "intrinsic_cost", 0.0):
                continue  # composite whole-body strategy (belt-and-braces)
            tp_plan[idx] = s
        return tp_plan

    # --------------------------------------------------------------- api

    def init_state(self, params, *example_batch):
        if self._built is None:
            if not example_batch:
                raise ValueError(
                    "first init_state call needs an example batch: "
                    "init_state(params, *batch)")
            self._build(params, example_batch)
            self._param_struct = _struct(params)
            return self._built[1](params)
        # re-init against the existing build: the stage plan and packed
        # layout were traced once, so a different geometry must rebuild
        # (a fresh instance), not silently re-pack through the stale plan
        pstruct = _struct(params)
        if pstruct != self._param_struct:
            raise ValueError(
                "params shape/dtype signature differs from the one this "
                "step was built with; build a new "
                "easydist_compile(pp_stages=...) instance")
        # non-float leaves were baked into the trace as CONSTANTS: a
        # re-init whose int tables/masks changed content would silently
        # compute with the old values (r5 review #2)
        import numpy as _np

        leaves = jax.tree_util.tree_leaves(params)
        for i, baked in self._const_baked.items():
            if not _np.array_equal(_np.asarray(leaves[i]),
                                   _np.asarray(baked)):
                raise ValueError(
                    "a non-float param leaf changed content since the "
                    "build; non-float leaves are baked into the traced "
                    "program as constants — build a new "
                    "easydist_compile(pp_stages=...) instance")
        if example_batch:
            bstruct = _struct(example_batch)
            if bstruct != self._batch_struct:
                raise ValueError(
                    f"batch signature {bstruct} differs from the build's "
                    f"{self._batch_struct}; build a new "
                    f"easydist_compile(pp_stages=...) instance")
        return self._built[1](params)

    def __call__(self, state, *batch):
        if self._built is None:
            raise RuntimeError("call init_state(params, *batch) first")
        # the stage plan and transport layout were traced at the build
        # batch shape; a different (even divisible) shape would replay the
        # stale plan on phantom pad rows and return silently-wrong losses
        struct = _struct(batch)
        if struct != self._batch_struct:
            raise ValueError(
                f"batch shape/dtype signature {struct} differs from the "
                f"one this step was built with {self._batch_struct}; "
                f"build a separate easydist_compile(pp_stages=...) "
                f"instance per batch geometry")
        return self._built[0](state, *batch)

    def export_state_dict(self, state):
        """Unpack a live train state back to the LOGICAL params pytree.

        `init_state` packs stage-exclusive float leaves into the sharded
        [n_stages, max_elems] transport buffer; a checkpoint of the raw
        state is therefore useless to anything but the exact same build
        (eval harnesses, exporters, a re-build at different pp_stages).
        This inverts it: gather the packed rows, slice each leaf back out
        per the stage layouts, merge the shared leaves and the baked
        non-float constants, and unflatten to the original params tree.

        The f32 transport holds f32/bf16/f16 leaves exactly, so
        init_state(export_state_dict(state)) repacks BITWISE-identically
        (tested in tests/test_resilience/test_export_state.py); optimizer
        state is intentionally not exported — it lives in the packed
        representation and only round-trips through a same-build
        checkpoint.
        """
        if self._built is None:
            raise RuntimeError("call init_state(params, *batch) first")
        pack_params = self._built[2]
        unpack = getattr(pack_params, "unpack_params", None)
        if unpack is None:
            raise RuntimeError(
                "this build did not pack params (shard_params off); the "
                "state already holds logical leaves")
        packed, shared = state[0]
        # host gather first: the packed buffer is sharded pp x siblings,
        # and the slicing below is host-side bookkeeping, not device work.
        # Chunked fetch (reshard/) so the host never stages more than one
        # shard + one chunk beyond the output buffer — at real model scale
        # the packed transport buffer is the largest live array there is.
        from easydist_tpu import reshard

        packed = reshard.fetch_chunked(packed)
        shared = tuple(jax.device_get(s) for s in shared)
        diff_leaves = unpack((jnp.asarray(packed),
                              tuple(jnp.asarray(s) for s in shared)))
        n_all = len(self._diff_idx) + len(self._const_baked)
        out = [None] * n_all
        for i, leaf in zip(self._diff_idx, diff_leaves):
            out[i] = leaf
        for i, baked in self._const_baked.items():
            out[i] = baked
        return jax.tree_util.tree_unflatten(self._params_treedef, out)
