"""One-decorator hybrid auto-PP x auto-SPMD (VERDICT r3 missing #4).

`easydist_compile(loss_fn, pp_stages=S, n_microbatches=M, mesh=mesh)` takes
an UNMODIFIED loss function `loss_fn(params, *batch) -> scalar` and returns
a compiled TRAIN STEP over a pp x (anything) mesh:

  1. the loss is traced at microbatch shape and auto-split into S
     FLOP-balanced stages (`parallel/auto_pipeline._StagePlan`; user
     `split_point` markers honored)
  2. stage-exclusive params are packed per stage and sharded over the pp
     axis AND (flat dim) over every other mesh axis — per-device param
     bytes ~ total / n_devices, ZeRO-style
  3. the SPMD solver (`solve_axes`) runs on the loss jaxpr over the NON-pp
     mesh axes; its chosen placements become `with_sharding_constraint`s
     replayed inside each stage branch.  The pipeline shard_maps manually
     over ONLY the pp axis (partial-manual), so those sibling axes stay
     GSPMD-auto and the constraints hold INSIDE stages — solver-sharded
     tensors inside auto-split stages
  4. jax autodiff through the ppermute pipeline yields the backward
     schedule; the optimizer (traced Adam/SGD from models/optim.py) runs
     elementwise directly on the packed representation

Reference equivalent: passing `schedule_cls` to the same compile entry
(easydist/torch/compile_auto.py:683-715) — there the stages are per-rank
processes with DTensor-sharded submodules over NCCL; here one partial-
manual SPMD program over ICI.

Schedules: "gpipe" (fill-drain + autodiff backward) and "remat" (gpipe
with per-stage rematerialization).  True supertick 1F1B exists for
homogeneous stage stacks (`parallel/pipeline.spmd_pipeline_grad`); the
auto-split path raises a pointer there rather than mislabeling gpipe.
"""

from __future__ import annotations

import logging
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend import core as jex_core
from jax.sharding import NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)


def _non_pp_axis_specs(mesh, pp_axis):
    from .mesh import get_axis_specs

    return [s for s in get_axis_specs(mesh) if s.name != pp_axis]


def _solve_intra_stage(closed_jaxpr, mesh, pp_axis):
    """Run discovery + the per-axis solver over the non-pp mesh axes;
    returns {eqn_idx: [NamedSharding|None per invar]} constraints."""
    from .api import _combined_spec, solve_axes
    from .interpreter import ShardingAnalyzer

    axis_specs = _non_pp_axis_specs(mesh, pp_axis)
    if not axis_specs or all(s.size == 1 for s in axis_specs):
        return {}
    world = min(s.size for s in axis_specs)
    analyzer = ShardingAnalyzer(closed_jaxpr, world_size=world)
    rules, shape_info = analyzer.run()
    per_axis, _ = solve_axes(closed_jaxpr, axis_specs, world, rules,
                             shape_info, analyzer.names)
    per_axis = [c if c is not None else {} for c in per_axis]
    axis_names = [s.name for s in axis_specs]

    constraints = {}
    for idx, eqn in enumerate(closed_jaxpr.jaxpr.eqns):
        strategies = [c.get(f"op{idx}") for c in per_axis]
        if all(s is None for s in strategies):
            continue
        specs = []
        var_pos = 0
        for v in eqn.invars:
            if isinstance(v, jex_core.Literal):
                specs.append(None)
                continue
            placements = [s.in_placements[var_pos]
                          if s is not None and var_pos < len(s.in_placements)
                          else None for s in strategies]
            ndim = len(getattr(v.aval, "shape", ()))
            if ndim > 0 and any(p is not None and p.is_shard()
                                for p in placements):
                spec = _combined_spec(placements, axis_names, ndim)
                specs.append(NamedSharding(mesh, spec))
            else:
                specs.append(None)
            var_pos += 1
        if any(sp is not None for sp in specs):
            constraints[idx] = specs
    return constraints


class PPCompiledFunction:
    """Hybrid-compiled train step.  Usage:

        compiled = easydist_compile(loss_fn, pp_stages=4,
                                    n_microbatches=8, mesh=mesh)
        state = compiled.init_state(params)       # packs + shards
        state, loss = compiled(state, *batch)     # one train step
    """

    def __init__(self, loss_fn: Callable, mesh, pp_stages: int,
                 n_microbatches: int, pp_axis: str = "pp",
                 schedule: str = "gpipe", lr: float = 1e-4,
                 optimizer: str = "adam"):
        if schedule not in ("gpipe", "remat"):
            raise NotImplementedError(
                f"schedule={schedule!r} on the auto-split path; supertick "
                f"1F1B needs homogeneous stages — use "
                f"parallel.pipeline.spmd_pipeline_grad (or "
                f"models.gpt.make_gpt_pipeline_step) for that")
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.pp_stages = pp_stages
        self.n_microbatches = n_microbatches
        self.pp_axis = pp_axis
        self.schedule = schedule
        self.lr = lr
        if optimizer not in ("adam", "sgd"):
            raise ValueError(f"unknown optimizer {optimizer!r}")
        self.optimizer = optimizer
        self._built = None  # (pipe, pack_params, jitted step, mb shapes)

    # ------------------------------------------------------------- build

    def _build(self, params, batch):
        from easydist_tpu.models.optim import (adam_init, adam_update,
                                               sgd_update)
        from easydist_tpu.parallel.auto_pipeline import pipeline_forward
        from .inline import inline_calls

        M = self.n_microbatches
        mesh, pp_axis = self.mesh, self.pp_axis
        if mesh.shape[pp_axis] != self.pp_stages:
            raise ValueError(
                f"mesh axis {pp_axis!r} has size {mesh.shape[pp_axis]}, "
                f"expected pp_stages={self.pp_stages}")

        def to_mb(x):
            if x.shape[0] % M != 0:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"n_microbatches={M}")
            return x.reshape((M, x.shape[0] // M) + x.shape[1:])

        mb_example = tuple(jax.tree_util.tree_map(lambda x: to_mb(x)[0],
                                                  b) for b in batch)

        # intra-stage SPMD solve over the non-pp axes
        closed = inline_calls(jax.make_jaxpr(self.loss_fn)(
            params, *mb_example))
        constraints = _solve_intra_stage(closed, mesh, pp_axis)
        logger.info("[pp-hybrid] %d eqns carry intra-stage constraints",
                    len(constraints))

        def loss_flat_mb(p, mb_tuple):
            return self.loss_fn(p, *mb_tuple)

        pipe, pack_params = pipeline_forward(
            loss_flat_mb, params, mb_example, mesh,
            n_stages=self.pp_stages, n_microbatches=M, axis=pp_axis,
            shard_params=True, auto_axes=True, eqn_constraints=constraints,
            remat_stages=(self.schedule == "remat"))

        # storage shardings: packed stage rows split over pp AND, flat,
        # over every sibling axis (params/device ~ total/n_devices)
        other_axes = tuple(s.name for s in _non_pp_axis_specs(mesh, pp_axis)
                           if s.size > 1)
        packed_sharding = NamedSharding(
            mesh, PartitionSpec(pp_axis, other_axes or None))
        update = adam_update if self.optimizer == "adam" else sgd_update

        def step(state, *batch_args):
            params_repr, opt = state
            mbs = tuple(jax.tree_util.tree_map(to_mb, b)
                        for b in batch_args)

            def loss_of(pr):
                losses = pipe(pr, mbs)  # [M] scalars
                return jnp.mean(losses)

            loss, grads = jax.value_and_grad(loss_of)(params_repr)
            if self.optimizer == "adam":
                new_repr, new_opt = update(params_repr, grads, opt,
                                           lr=self.lr)
            else:
                new_repr = update(params_repr, grads, lr=self.lr)
                new_opt = opt
            return (new_repr, new_opt), loss

        jitted = jax.jit(step, donate_argnums=(0,))

        def init_state(raw_params):
            repr_ = pack_params(raw_params)
            packed, shared = repr_
            placed = (jax.device_put(packed, packed_sharding), shared)
            opt = adam_init(placed) if self.optimizer == "adam" else ()
            return (placed, opt)

        self._built = (jitted, init_state, pack_params)
        return self._built

    # --------------------------------------------------------------- api

    def init_state(self, params, *example_batch):
        if self._built is None:
            if not example_batch:
                raise ValueError(
                    "first init_state call needs an example batch: "
                    "init_state(params, *batch)")
            self._build(params, example_batch)
        return self._built[1](params)

    def __call__(self, state, *batch):
        if self._built is None:
            raise RuntimeError("call init_state(params, *batch) first")
        return self._built[0](state, *batch)
