"""User-directed sharding scopes (reference: easydist/scope_auto — scope
markers grouping regions for per-scope strategies).

`fix_sharding(x, *axes)` pins a tensor's placement inside a compiled step;
the solver routes strategies around it and XLA enforces it.  This is the
manual-override escape hatch when the automatic plan should be constrained
(e.g. force megatron-style weight sharding for one layer).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import get_device_mesh

# mesh being compiled right now (set by compile_step around tracing), so
# fix_sharding inside a step targets the step's mesh even when the global
# mesh points elsewhere
_COMPILE_MESH = None


class _compile_mesh_ctx:
    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        global _COMPILE_MESH
        self._prev = _COMPILE_MESH
        _COMPILE_MESH = self.mesh

    def __exit__(self, *exc):
        global _COMPILE_MESH
        _COMPILE_MESH = self._prev


def fix_sharding(x, *spec_entries, mesh=None):
    """Pin `x` to PartitionSpec(*spec_entries) on the current mesh
    (the mesh under compilation, else the global mesh).

    Works inside functions decorated with `easydist_compile` and in plain
    jitted code alike.
    """
    mesh = mesh or _COMPILE_MESH or get_device_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec_entries)))
