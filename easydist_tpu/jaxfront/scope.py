"""User-directed sharding scopes (reference: easydist/scope_auto — scope
markers grouping regions for per-scope strategies).

`fix_sharding(x, *axes)` pins a tensor's placement inside a compiled step;
the solver routes strategies around it and XLA enforces it.  This is the
manual-override escape hatch when the automatic plan should be constrained
(e.g. force megatron-style weight sharding for one layer).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import get_device_mesh

# mesh being compiled right now (set by compile_step around tracing), so
# fix_sharding inside a step targets the step's mesh even when the global
# mesh points elsewhere
_COMPILE_MESH = None


class _compile_mesh_ctx:
    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        global _COMPILE_MESH
        self._prev = _COMPILE_MESH
        _COMPILE_MESH = self.mesh

    def __exit__(self, *exc):
        global _COMPILE_MESH
        _COMPILE_MESH = self._prev


def scoped_region(fn, mesh, axis_specs=None):
    """Solve `fn`'s sharding strategy on its OWN mesh and inline the region
    with that mesh's constraints wherever it is called — including inside a
    surrounding `easydist_compile` step running on a different mesh view.

    The reference groups model regions with scope markers and solves each
    scope's strategy separately (torch/scope_auto/scope_marker.py,
    build_scope_modules.py); on TPU the scope's mesh is just another
    logical view of the same devices, so the scoped strategy is emitted as
    `with_sharding_constraint`s over that view and XLA stitches the views
    together with resharding collectives at the scope boundary.

    Returns wrapped(*args) with fn's semantics.  The per-signature solve
    runs once and is cached.
    """
    _cache = {}

    def wrapped(*args):
        from .api import ShardingAnalyzer, emit_sharded_fn, solve_axes
        from .inline import inline_calls
        from .mesh import get_axis_specs

        flat, treedef = jax.tree_util.tree_flatten(args)
        key = (treedef, tuple((tuple(getattr(x, "shape", ())),
                               str(getattr(x, "dtype", type(x))))
                              for x in flat))
        hit = _cache.get(key)
        if hit is None:
            closed, out_tree = jax.make_jaxpr(fn, return_shape=True)(*args)
            closed = inline_calls(closed)
            specs = axis_specs or get_axis_specs(mesh)
            world = min((s.size for s in specs), default=1)
            analyzer = ShardingAnalyzer(closed, world_size=world)
            rules, shape_info = analyzer.run()
            # same per-axis loop as compile_step: cross-axis exclusion and
            # shape shrinking keep two axes off the same tensor dim
            per_axis, _ = solve_axes(closed, specs, world, rules,
                                     shape_info, analyzer.names)
            per_axis = [c if c is not None else {} for c in per_axis]
            sharded = emit_sharded_fn(closed, analyzer.names, per_axis,
                                      [s.name for s in specs], mesh)
            out_leaves_tree = jax.tree_util.tree_structure(out_tree)
            hit = _cache[key] = (sharded, out_leaves_tree)
        sharded, out_leaves_tree = hit
        outs = sharded(*flat)
        return jax.tree_util.tree_unflatten(out_leaves_tree, outs)

    return wrapped


def fix_sharding(x, *spec_entries, mesh=None):
    """Pin `x` to PartitionSpec(*spec_entries) on the current mesh
    (the mesh under compilation, else the global mesh).

    Works inside functions decorated with `easydist_compile` and in plain
    jitted code alike.
    """
    mesh = mesh or _COMPILE_MESH or get_device_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec_entries)))
