"""User-directed sharding scopes (reference: easydist/scope_auto — scope
markers grouping regions for per-scope strategies).

`fix_sharding(x, *axes)` pins a tensor's placement inside a compiled step;
the solver routes strategies around it and XLA enforces it.  This is the
manual-override escape hatch when the automatic plan should be constrained
(e.g. force megatron-style weight sharding for one layer).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import get_device_mesh


def fix_sharding(x, *spec_entries, mesh=None):
    """Pin `x` to PartitionSpec(*spec_entries) on the (global) mesh.

    Works inside functions decorated with `easydist_compile` and in plain
    jitted code alike.
    """
    mesh = mesh or get_device_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec_entries)))
