"""Jaxpr sharding interpreter: run every equation through ShardCombine.

Walks a jaxpr equation by equation, materializes random concrete inputs on
the host CPU, wraps each primitive bind as a `MetaOp`, and runs sharding
discovery — with a per-(primitive, shapes, params) cache and a prompt
fast-path so each unique op signature is discovered once.  Reshapes are
handled analytically (`view_rule`) instead of by execution.

Reference: easydist/jax/sharding_interpreter.py:51-170.  Differences: var
names are assigned stably (v0, v1, ...) instead of parsing jaxpr printouts,
and avals stay abstract in the environment — inputs are materialized only at
op-execution time, bounding discovery memory to one op's working set.
"""

from __future__ import annotations

import logging
import time
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.extend import core as jex_core

from easydist_tpu import config as edconfig
from easydist_tpu.metashard import MetaOp, ShardSpace, view_rule
from easydist_tpu.metashard.metaop import probe_calls

logger = logging.getLogger(__name__)

# primitives whose sharding rule is computed analytically, not by execution
_VIEW_PRIMS = {"reshape"}

# preset rules the execution harness cannot cross-check: their analytic
# claims hold under GSPMD but the eager probe rejects the sharded rebind
# (absolute-shape params like slice limits / broadcast out-shapes, or
# unpartitionable custom calls) — documented per-rule in presets.py
_CROSSCHECK_SKIP = {
    "gather", "scatter-add", "pallas_call", "sharding_constraint",
    "slice", "broadcast_in_dim", "reshape", "dynamic_slice",
    "dynamic_update_slice", "iota", "ed_attention_fwd", "ed_attention_bwd",
}


def _recombine_matches(expected, got) -> bool:
    """Compare a preset recombine (functools.partial over Recombine.*)
    against what execution discovery matched, up to default halo/block."""
    if expected is None or got is None:
        return expected is None and got is None
    if isinstance(expected, list) or isinstance(got, list):
        if not isinstance(expected, list) or not isinstance(got, list) \
                or len(expected) != len(got):
            return False
        return all(_recombine_matches(e, g)
                   for e, g in zip(expected, got))

    def norm(fn):
        kw = dict(getattr(fn, "keywords", {}) or {})
        if kw.get("halo") == 0:
            del kw["halo"]
        if kw.get("block") == 1:
            del kw["block"]
        return getattr(getattr(fn, "func", None), "__name__", None), kw

    return norm(expected) == norm(got)


class VarNames:
    """Stable names for jaxpr Vars (jax no longer prints short names)."""

    def __init__(self):
        self._names: Dict[jex_core.Var, str] = {}

    def name(self, var) -> str:
        if var not in self._names:
            self._names[var] = f"v{len(self._names)}"
        return self._names[var]


def _materialize(aval, key):
    """Random concrete array for an abstract value (reference jax/api.py:50-61).
    Random (not ones/zeros) so degenerate recombinations don't false-match.
    Floats are strictly POSITIVE (uniform [0.5, 1.5], matching the int
    convention below): signed values make contraction outputs cancel to
    near zero, where the reassociated per-shard partial sums miss the
    allclose atol and a valid reduce candidate is rejected for one shape
    but accepted for a same-role sibling — acceptance must be a function
    of the op's structure, not of which random draws cancelled."""
    name = aval.dtype.name
    if name in ("float64", "float32", "float16", "bfloat16"):
        return jax.random.uniform(key, shape=aval.shape, dtype=aval.dtype,
                                  minval=0.5, maxval=1.5)
    if name in ("int64", "int32", "int16", "int8", "uint8", "uint32", "uint64"):
        return jax.random.randint(key, shape=aval.shape, minval=1, maxval=8,
                                  dtype=aval.dtype)
    if name == "bool":
        return jax.random.bernoulli(key, p=0.5, shape=aval.shape)
    return jnp.zeros(aval.shape, dtype=aval.dtype)


def hash_array_bytes(arr) -> str:
    """Content digest of an array's full bytes — used wherever constant
    VALUES (not just shapes) must feed a cache key; repr() truncates."""
    import hashlib

    import numpy as np

    arr = np.ascontiguousarray(arr)
    return hashlib.sha256(arr.tobytes()).hexdigest()[:16]


def eqn_signature(eqn, names: VarNames) -> str:
    """Cache key for an equation: primitive + params + input shapes/dtypes."""
    import numpy as np

    prim = eqn.primitive.name
    parts = []
    for v in eqn.invars:
        if isinstance(v, jex_core.Literal):
            val = v.val
            if isinstance(val, np.ndarray) and val.size > 1:
                parts.append(f"lit:{val.dtype.name}{list(val.shape)}:"
                             f"{hash_array_bytes(val)}")
            else:
                parts.append(f"lit:{val!r}")
        else:
            parts.append(f"{v.aval.dtype.name}{list(v.aval.shape)}")
    try:
        params = str(sorted(eqn.params.items()))
    except Exception:
        params = str(eqn.params)
    return f"{prim}|{';'.join(parts)}|{params}"


class ShardingAnalyzer:
    """Discover sharding rules for every eqn of a (closed) jaxpr."""

    def __init__(self, closed_jaxpr, world_size: int, seed: int = 42):
        from .discovery import DiscoveryCounters, get_cache

        self.closed_jaxpr = closed_jaxpr
        self.jaxpr = closed_jaxpr.jaxpr
        self.world_size = world_size
        self.names = VarNames()
        self.key = jax.random.PRNGKey(seed)
        self._eqn_key = self.key
        self._eqn_draws = 0
        # eqn signature -> {"space": ShardSpace, "recombines": {...}}
        self.rules: Dict[str, dict] = {}
        # primitive name -> first discovered space (prompt for other shapes)
        self.prompts: Dict[str, ShardSpace] = {}
        self.shape_info: Dict[str, Tuple[Tuple[int, ...], str]] = {}
        # propagation groups (jaxfront/discovery.py): canonical signature ->
        # (rule, representative row shapes, representative exact signature)
        self.canon_rules: Dict[str, tuple] = {}
        self.counters = DiscoveryCounters()
        # DISC001/DISC002 findings + transfer records for the layer-10 audit
        self.findings: List[object] = []
        self._transfers: List[dict] = []
        self._dcache = get_cache()
        self._is_sub = False
        self._last_discovery_failed = False

    def _next_key(self):
        """Key for the next materialized discovery input.  Derived from
        (base seed, current eqn signature, draw index) — NOT a sequential
        split stream — so an eqn's probe inputs are identical no matter
        which earlier eqns were served from a group, the cache, or a
        preset.  Positional keys would make discovery outcomes depend on
        pruning history and break pruned-vs-unpruned strategy equality."""
        k = jax.random.fold_in(self._eqn_key, self._eqn_draws)
        self._eqn_draws += 1
        return k

    def run(self) -> Tuple[Dict[str, dict], Dict[str, Tuple]]:
        env: Dict[jex_core.Var, object] = {}

        def read_concrete(var):
            if isinstance(var, jex_core.Literal):
                return var.val
            aval = env[var]
            if hasattr(aval, "shape") and hasattr(aval, "dtype"):
                with jax.default_device(_discovery_device()):
                    return _materialize(aval, self._next_key())
            return aval

        def _discovery_device():
            if edconfig.discovery_on_cpu:
                return jax.local_devices(backend="cpu")[0]
            return jax.devices()[0]

        t0 = time.perf_counter()
        p0 = probe_calls()

        for var in self.jaxpr.invars + self.jaxpr.constvars:
            env[var] = var.aval
            self.shape_info[self.names.name(var)] = (tuple(var.aval.shape),
                                                     var.aval.dtype.name)

        for eqn in self.jaxpr.eqns:
            sig = eqn_signature(eqn, self.names)

            if sig not in self.rules:
                self._eqn_key = jax.random.fold_in(
                    self.key, zlib.crc32(sig.encode()))
                self._eqn_draws = 0
                self.rules[sig] = self._lookup_or_discover(eqn, sig,
                                                           read_concrete)

            # record output shapes from avals (no execution needed)
            for outvar in eqn.outvars:
                aval = outvar.aval
                env[outvar] = aval
                if hasattr(aval, "shape"):
                    self.shape_info[self.names.name(outvar)] = (
                        tuple(aval.shape), aval.dtype.name)

        if not self._is_sub:
            self._finish_trace(time.perf_counter() - t0, probe_calls() - p0)
        return self.rules, self.shape_info

    def _finish_trace(self, elapsed: float, probes: int) -> None:
        """Top-level-trace epilogue: fold the probe/derivation counts into
        this trace's counters (and the process-wide ones), persist newly
        discovered rules, audit every representative->member transfer
        (analyze layer 10), and log ONE summary line for the whole trace —
        the per-op discovery chatter is debug-level now."""
        from .discovery import GLOBAL_COUNTERS

        c = self.counters
        c.discovery_seconds += elapsed
        c.probes_compiled += probes
        c.groups = len(self.canon_rules)
        if self._dcache is not None:
            self._dcache.flush()
        if edconfig.enable_analyze and self._transfers:
            from easydist_tpu.analyze import audit_rule_transfer

            self.findings.extend(audit_rule_transfer(self._transfers))
        GLOBAL_COUNTERS.merge(c)
        logger.info(
            "[discovery] %d signatures: %d preset, %d grouped, %d cached, "
            "%d discovered (%d probes, %d groups) in %.2fs",
            len(self.rules), c.rules_preset, c.rules_from_group,
            c.rules_from_cache, c.rules_discovered, c.probes_compiled,
            c.groups, c.discovery_seconds)

    def _lookup_or_discover(self, eqn, sig: str, read_concrete) -> dict:
        """Rule resolution pipeline for one unseen exact signature:
        analytic preset -> propagation group (discover once per canonical
        signature, instantiate for members) -> persistent rule cache ->
        execution/composite discovery.  The kill switch
        (EASYDIST_DISCOVERY_PRUNE=0) reduces this to preset-or-discover,
        the pre-pruning behavior."""
        from . import discovery as disc
        from .presets import _RULES as preset_registry, preset_rule

        prim_name = eqn.primitive.name
        if edconfig.discovery_use_presets:
            preset = preset_rule(eqn, self.world_size)
            if preset is not None:
                self.counters.rules_preset += 1
                if edconfig.discovery_crosscheck:
                    self._crosscheck_preset(eqn, sig, preset, read_concrete)
                return preset
            if prim_name in preset_registry \
                    and prim_name not in _VIEW_PRIMS \
                    and edconfig.enable_analyze:
                from easydist_tpu.analyze import make_finding

                # DISC002: a preset-covered primitive fell through to the
                # probe harness — the analytic rule declined this instance
                self.findings.append(make_finding(
                    "DISC002", f"discovery.{prim_name}",
                    f"analytic preset for {prim_name!r} declined "
                    f"{sig[:96]!r}; execution discovery runs instead — "
                    f"extend the preset to cover this instance or fix "
                    f"the decline"))

        csig = None
        if edconfig.discovery_prune or self._dcache is not None:
            csig = disc.canonical_signature(eqn, self.world_size)

        if csig is not None and edconfig.discovery_prune:
            got = self.canon_rules.get(csig)
            if got is not None:
                rule, rep_shapes, rep_sig = got
                if disc.rule_transferable(rule, rep_shapes, eqn):
                    self.counters.rules_from_group += 1
                    self._transfers.append({
                        "sig": sig, "prim": prim_name, "rep_sig": rep_sig,
                        "rep_shapes": rep_shapes,
                        "member_shapes": disc.eqn_tensor_shapes(eqn),
                        "rule": rule})
                    return rule

        if csig is not None and self._dcache is not None:
            entry = self._dcache.get(csig)
            if entry is not None and disc.rule_transferable(
                    entry["rule"], entry["shapes"], eqn):
                self.counters.rules_from_cache += 1
                self._transfers.append({
                    "sig": sig, "prim": prim_name, "rep_sig": "<cache>",
                    "rep_shapes": entry["shapes"],
                    "member_shapes": disc.eqn_tensor_shapes(eqn),
                    "rule": entry["rule"]})
                if edconfig.discovery_prune:
                    self.canon_rules[csig] = (entry["rule"],
                                              entry["shapes"], sig)
                return entry["rule"]

        self._last_discovery_failed = False
        rule = self._discover_eqn(eqn, sig, read_concrete)
        self.counters.rules_discovered += 1
        if csig is not None and not self._last_discovery_failed:
            shapes = disc.eqn_tensor_shapes(eqn)
            if edconfig.discovery_prune:
                self.canon_rules[csig] = (rule, shapes, sig)
            if self._dcache is not None:
                self._dcache.put(csig, {"rule": rule, "shapes": shapes,
                                        "prim": prim_name})
        return rule

    def _crosscheck_preset(self, eqn, sig: str, rule: dict,
                           read_concrete) -> None:
        """One-shot preset validation (EASYDIST_DISCOVERY_CROSSCHECK=1):
        every shard group the analytic rule declares must execute through
        the ShardCombine harness and recombine exactly as declared.  A
        failure is counted and logged loudly, never raised — the mode
        exists to audit the preset bank, not to gate compiles."""
        prim_name = eqn.primitive.name
        space = rule.get("space")
        if prim_name in _CROSSCHECK_SKIP or space is None \
                or space.max_group() == 0:
            return
        total = sum(int(np.prod(v.aval.shape))
                    for v in list(eqn.invars) + list(eqn.outvars)
                    if not isinstance(v, jex_core.Literal)
                    and hasattr(getattr(v, "aval", None), "shape"))
        if total > edconfig.discovery_hint_numel:
            return  # cross-check runs on small shapes only

        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)

        def bind_fn(*tensors, **params):
            with jax.disable_jit():
                return eqn.primitive.bind(*subfuns, *tensors, **params)

        invals = [read_concrete(v) for v in eqn.invars]
        op = MetaOp(bind_fn, tuple(invals), kwargs=bind_params,
                    name=prim_name)
        if len(space) != len(op.tensor_indices):
            return  # row convention mismatch (array literal rows)
        try:
            global_out = op.run_global()
        except Exception:
            return
        self.counters.crosscheck_checked += 1
        for group in range(1, space.max_group() + 1):
            res = op._check_candidate(space, group, global_out)
            ok = (res is not None and res[1] is None
                  and _recombine_matches(rule["recombines"].get(group),
                                         res[0]))
            if not ok:
                self.counters.crosscheck_failures += 1
                logger.warning(
                    "[discovery] preset cross-check FAILED for %s group "
                    "%d (%s)", prim_name, group, sig[:120])

    def _discover_eqn(self, eqn, sig: str, read_concrete) -> dict:
        """Actually derive a rule for one eqn (view analysis, composite body
        solving, or execution discovery).  Preset lookup and all reuse paths
        live in _lookup_or_discover; this runs only on a full miss."""
        prim_name = eqn.primitive.name

        if prim_name in _VIEW_PRIMS:
            in_aval = eqn.invars[0].aval
            out_aval = eqn.outvars[0].aval
            try:
                rule = view_rule(list(in_aval.shape), list(out_aval.shape),
                                 world_size=self.world_size)
                return {"space": rule["space"], "recombines": rule["recombines"]}
            except RuntimeError:
                pass  # unalignable view: fall through to execution discovery

        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)

        def bind_fn(*tensors, **params):
            with jax.disable_jit():
                return eqn.primitive.bind(*subfuns, *tensors, **params)

        # hint shrink (reference get_hint_size, sharding_interpreter.py:
        # 256-313): execution discovery on a huge unpreset op would run it
        # eagerly nshards x candidates times — discover on a proportionally
        # shrunk instance instead.  Equal dim sizes shrink together (keeps
        # contraction/broadcast consistency); rules are dim-indexed so they
        # transfer to the original shapes.  Ops whose params encode shapes
        # fail the shrunk bind and fall through to full-size discovery.
        total = sum(int(np.prod(v.aval.shape)) for v in eqn.invars
                    if not isinstance(v, jex_core.Literal)
                    and hasattr(v.aval, "shape"))
        total += sum(int(np.prod(v.aval.shape)) for v in eqn.outvars
                     if hasattr(v.aval, "shape"))
        # jax.checkpoint bodies: recursively analyze the inner jaxpr and
        # compose a rule analytically — execution discovery would run the
        # whole body eagerly per candidate (reference r1 gap: remat regions
        # fell back to replicate)
        if prim_name in ("remat2", "remat", "checkpoint"):
            rule = self._discover_composite(eqn)
            if rule is not None:
                return rule

        # lax.scan: recursive body analysis with carry-placement threading —
        # without it a scan-over-layers model (the idiomatic Llama-scale
        # form) ships fully replicated.  The reference never hits this
        # because make_fx fully unrolls (easydist/torch/compile.py:78-83);
        # the TPU design keeps the rolled loop (XLA compiles the body once)
        # and instead solves the body, pricing per-iteration collectives as
        # the scan strategy's intrinsic cost.
        if prim_name == "scan" and self.world_size > 1:
            try:
                rule = self._discover_scan(eqn)
            except Exception as e:
                logger.warning("scan discovery failed (%s): %s", sig, e)
                rule = None
            if rule is not None:
                return rule

        # lax.cond / lax.while_loop: same composite treatment (VERDICT r4
        # missing #4 — any non-scan control flow shipped replicated)
        if prim_name == "cond" and self.world_size > 1:
            try:
                rule = self._discover_cond(eqn)
            except Exception as e:
                logger.warning("cond discovery failed (%s): %s", sig, e)
                rule = None
            if rule is not None:
                return rule
        if prim_name == "while" and self.world_size > 1:
            try:
                rule = self._discover_while(eqn)
            except Exception as e:
                logger.warning("while discovery failed (%s): %s", sig, e)
                rule = None
            if rule is not None:
                return rule

        if total > edconfig.discovery_hint_numel:
            rule = self._discover_shrunk(eqn, bind_fn, bind_params,
                                         prim_name)
            if rule is not None:
                logger.debug("discovery hint-shrink applied to %s (%d elems)",
                             prim_name, total)
                return rule

        invals = [read_concrete(v) for v in eqn.invars]
        op = MetaOp(bind_fn, tuple(invals), kwargs=bind_params,
                    name=prim_name)
        prompt = self.prompts.get(prim_name)
        try:
            space, recombines = op.discover(prompt=prompt)
        except Exception as e:
            logger.warning("discovery failed for %s (%s): %s — replicating",
                           prim_name, sig, e)
            space, recombines = ShardSpace.for_args(op.flat_args), {}
            # a replicate fallback is shape-circumstantial — never persist
            # it or transfer it across a propagation group
            self._last_discovery_failed = True
        if prim_name not in self.prompts and space.max_group() > 0:
            self.prompts[prim_name] = space
        return {"space": space, "recombines": recombines}

    def _analyze_inner(self, inner):
        """Normalize a call-like eqn's body jaxpr and analyze it with this
        analyzer's caches shared.  Returns (inner ClosedJaxpr, sub analyzer,
        rules, shape_info) or None when the body isn't analyzable."""
        from .inline import inline_calls

        if inner is None:
            return None
        if not hasattr(inner, "jaxpr"):  # raw Jaxpr -> ClosedJaxpr
            if inner.constvars:
                return None
            inner = jex_core.ClosedJaxpr(inner, ())
        inner = inline_calls(inner)  # bodies keep nested pjit calls

        sub = ShardingAnalyzer(inner, world_size=self.world_size)
        sub.prompts = self.prompts  # share caches with the outer analysis
        sub.rules = self.rules
        sub.canon_rules = self.canon_rules
        sub.counters = self.counters
        sub.findings = self.findings
        sub._transfers = self._transfers
        sub._dcache = self._dcache
        sub._is_sub = True  # the top-level trace owns probe/time accounting
        rules, shape_info = sub.run()
        return inner, sub, rules, shape_info

    def _discover_composite(self, eqn):
        """Priced whole-region strategies for a call-like eqn
        (jax.checkpoint body): analyze the inner jaxpr recursively, then
        solve the body graph once per seed input-dim with collectives
        PRICED, not forbidden (the scan/cond/while treatment) — each
        surviving assignment becomes one explicit strategy of the
        composite eqn carrying honest per-strategy compute seconds.

        The earlier dim-group table with free boundaries mispriced remat
        regions two ways: the outer solver's any-shard discount cut the
        WHOLE region's FLOPs 1/n for a strategy that sharded one residual
        chain and replicated everything else, and sync-free-only
        propagation dropped assignments whose optimum includes a priced
        mid-body psum.  Policy checkpoints (remat="dots") exposed both —
        their backward regions take saved dot residuals as extra
        operands, a degenerate seq-dim group over one residual won on
        boundary bytes, and the plan shipped mostly-replicated compute
        plus boundary all-to-alls the un-remat'd twin never emits
        (test_remat_gpt_plan_matches_unremat_twin[dots]).
        """
        from easydist_tpu.metashard.metair import Placement

        got = self._analyze_inner(eqn.params.get("jaxpr"))
        if got is None:
            return None
        inner, sub, rules, shape_info = got

        in_rows = [v for v in eqn.invars
                   if not isinstance(v, jex_core.Literal)]
        inner_invars = inner.jaxpr.invars
        if len(in_rows) != len(inner_invars):
            return None
        in_names = [sub.names.name(v) for v in inner_invars]
        out_names = [None if isinstance(v, jex_core.Literal)
                     else sub.names.name(v) for v in inner.jaxpr.outvars]

        strategies = []  # (in_placements, out_placements, comm, compute)
        seen_keys = set()
        covered = set()  # (invar row, dim) already sharded by a strategy
        full_compute = 0.0
        n_solves = 0
        for row, (v, name) in enumerate(zip(inner_invars, in_names)):
            shape = tuple(v.aval.shape)
            numel = int(np.prod(shape)) if shape else 1
            # bias-sized inputs may ride along in a solve, but never seed
            if numel < self.world_size * 64:
                continue
            for d, size in enumerate(shape):
                if size % self.world_size != 0 or size < self.world_size:
                    continue
                if (row, d) in covered:
                    continue
                if n_solves >= edconfig.scan_max_seed_solves:
                    break
                n_solves += 1
                res = self._solve_body_pinned(
                    inner, sub, rules, shape_info,
                    pins={name: Placement.shard(d)})
                if res is None:
                    continue
                var_p, comm, compute, full = res
                full_compute = full
                ins = []
                for nm in in_names:
                    p = var_p.get(nm)
                    ins.append(Placement.shard(p.dim)
                               if p is not None and p.is_shard()
                               else Placement.replicate())
                if all(p.is_replicate() for p in ins):
                    continue
                outs = []
                for nm in out_names:
                    p = var_p.get(nm) if nm is not None else None
                    if p is not None and p.is_shard():
                        outs.append(Placement.shard(p.dim))
                    elif p is not None and p.is_partial():
                        outs.append(Placement.partial())
                    else:
                        outs.append(Placement.replicate())
                key = (tuple(repr(p) for p in ins),
                       tuple(repr(p) for p in outs))
                if key in seen_keys:
                    continue
                seen_keys.add(key)
                strategies.append((ins, outs, comm, compute))
                for r2, p in enumerate(ins):
                    if p.is_shard():
                        covered.add((r2, p.dim))

        if not strategies:
            return None
        logger.debug("composite rule for %s: %d priced strategies",
                    eqn.primitive.name, len(strategies))
        # same-basis replicate price (see _solve_body_pinned)
        return {"space": None, "recombines": {},
                "strategies": strategies, "compute": full_compute}

    def _solve_body_pinned(self, inner, sub, rules, shape_info, pins,
                           state_io=None, replicate_names=()):
        """Solve a control-flow body graph with `pins` ({placeholder name:
        Placement}) enforced via strategy exclusion, pricing collectives.
        `replicate_names` additionally pins those placeholders to R.
        `state_io` threads loop carries (out -> init placeholder) so
        per-iteration reshards are priced, not forbidden.  Returns
        ({var name: Placement}, comm seconds, compute seconds,
        full-price compute seconds) or None (infeasible, or divisibility
        removed a pin)."""
        from easydist_tpu.autoflow import MeshAxisSpec, SpmdSolver
        from .bridge import jaxpr_to_metagraph

        axis = MeshAxisSpec("_body", self.world_size)
        g = jaxpr_to_metagraph(inner, rules, shape_info,
                               world_size=self.world_size,
                               names=sub.names, state_io=state_io or None)
        _inject_partial_propagation(g, self.world_size)
        replicate_names = set(replicate_names)

        def excl(node):
            target = pins.get(node.name)
            if target is not None:
                return [s for s in node.strategy_pool(self.world_size)
                        if repr(s.out_placements[0]) != repr(target)]
            if node.name in replicate_names:
                return [s for s in node.strategy_pool(self.world_size)
                        if not s.is_all_replicate()]
            return []

        # level 0 (one node per cluster): cone back-build only keeps
        # sync-free intra-cluster assignments, which would hide e.g.
        # TP's P->R psum edge from the pricing
        g.coarsen(self.world_size, level=0, exclude_map=excl)
        try:
            # cluster dedup ties strategies across same-signature clusters,
            # which would fight the per-placeholder pins — disable it for
            # this solve only (not process-wide)
            solver = SpmdSolver(g, axis, free_outputs=True,
                                cluster_dedup=False)
            chosen = solver.solve()
        except Exception:
            return None
        for name, target in pins.items():
            got = chosen.get(name)
            if got is None or repr(got.out_placements[0]) != repr(target):
                return None  # divisibility removed the pin
        comm = solver.assignment_comm_cost(chosen)
        if not np.isfinite(comm):
            return None
        var_p = {}
        for node in list(g.ops) + list(g.inputs):
            s = chosen.get(node.name)
            if s is None:
                continue
            for v, p in zip(node.outvars, s.out_placements):
                if v is not None and p is not None:
                    var_p[v.name] = p
        # per-op body compute under this assignment: the same op-time model
        # the overlap engine uses (MXU ops at peak_flops, memory-bound ops
        # at hbm_bandwidth — VERDICT r4 weak #7: a bytes-only proxy
        # under-prices MXU-bound transformer bodies by ~D/245 at f32),
        # with the outer solver's any-S 1/world discount per op
        from easydist_tpu.autoflow.reachability import node_seconds

        compute = full_compute = 0.0
        for node in g.ops:
            s = chosen.get(node.name)
            sharded = s is not None and any(
                p is not None and p.is_shard()
                for p in list(s.out_placements) + list(s.in_placements))
            sec = node_seconds(node)
            full_compute += sec
            compute += sec * (1.0 / self.world_size if sharded else 1.0)
        # full_compute is the SAME-BASIS replicate price: the outer solver
        # compares strat.compute_cost against the node's compute_proxy, so
        # both must come from one op-time model or replication wins by
        # accounting artifact alone
        return var_p, comm, compute, full_compute

    def _discover_scan(self, eqn):
        """Composite rule for `lax.scan`: analyze the body recursively, then
        solve the body graph once per seed input-dim with the carry threaded
        back to its init placeholder (a state_io edge prices the
        per-iteration reshard, so e.g. megatron TP's in-loop psum is priced,
        not forbidden).  Each surviving assignment becomes one shard group
        of the scan eqn whose `intrinsic_cost` = length x body collective
        seconds — the outer ILP weighs it against boundary resharding.

        Dim mapping: consts and carry rows map 1:1 into the body; xs/ys lose
        their leading scan axis (outer dim d <-> body dim d-1; dim 0 itself
        is the loop and never shards).

        Emission needs no body rewrite: constraining the outer scan operands
        (stacked params, init carry, xs) lets XLA's GSPMD partitioner
        propagate into the while loop and place the in-loop collectives —
        the standard rolled-layers form (MaxText/T5X style).
        """
        from easydist_tpu.autoflow import MeshAxisSpec, SpmdSolver
        from easydist_tpu.metashard.metair import Placement
        from .bridge import jaxpr_to_metagraph

        params = eqn.params
        num_consts = int(params.get("num_consts", 0))
        num_carry = int(params.get("num_carry", 0))
        length = int(params.get("length", 1))
        got = self._analyze_inner(params.get("jaxpr"))
        if got is None:
            return None
        inner, sub, rules, shape_info = got

        body_invars = inner.jaxpr.invars
        if len(eqn.invars) != len(body_invars):
            return None
        in_names = [sub.names.name(v) for v in body_invars]
        body_outvars = inner.jaxpr.outvars
        out_names = [None if isinstance(v, jex_core.Literal)
                     else sub.names.name(v) for v in body_outvars]

        # carry threading: body outvar k loops back into invar num_consts+k
        carry_io = {}
        for k in range(num_carry):
            if out_names[k] is not None:
                carry_io[out_names[k]] = in_names[num_consts + k]

        axis = MeshAxisSpec("_scan", self.world_size)
        carry_names = set(in_names[num_consts:num_consts + num_carry])

        def solve_with_seed(seed_name, seed_dim, carries_replicate=False):
            """Solve the body with the seed placeholder pinned; returns
            ({var name: Placement}, body comm seconds, compute) or None.
            `carries_replicate` pins every carry to R so weight seeds
            produce tensor-parallel assignments (otherwise free R->S slices
            let batch-sharding dominate every solve)."""
            return self._solve_body_pinned(
                inner, sub, rules, shape_info,
                pins={seed_name: Placement.shard(seed_dim)},
                state_io=carry_io,
                replicate_names=carry_names - {seed_name}
                if carries_replicate else ())

        # graph-edge rows: every non-Literal invar, in order (bridge.py
        # builds MetaNode.invars the same way)
        edge_invars = [i for i, v in enumerate(eqn.invars)
                       if not isinstance(v, jex_core.Literal)]
        n_xs_start = num_consts + num_carry
        strategies = []  # (in_placements, out_placements, cost)
        seen_keys = set()
        covered = set()  # (invar idx, outer dim) already sharded by a strat

        def extract(var_p):
            """Whole-body assignment -> (outer in placements, outer out
            placements) with xs/ys dims shifted past the scan axis."""
            ins = []
            for i in edge_invars:
                p = var_p.get(in_names[i])
                if p is None or not p.is_shard():
                    ins.append(Placement.replicate())
                    continue
                outer_dim = p.dim + 1 if i >= n_xs_start else p.dim
                shape = tuple(eqn.invars[i].aval.shape)
                if shape[outer_dim] % self.world_size != 0:
                    return None  # inconsistent mapping; be safe
                ins.append(Placement.shard(outer_dim))
            if all(p.is_replicate() for p in ins):
                return None
            outs = []
            for k, name in enumerate(out_names):
                if k < num_carry:
                    # authoritative carry placement is the init placeholder's
                    # (a mismatched body output pays its priced reshard
                    # inside the loop; GSPMD converges to the same fixed
                    # point at emission)
                    p = var_p.get(in_names[num_consts + k])
                    outs.append(p if p is not None and p.is_shard()
                                else Placement.replicate())
                else:
                    p = var_p.get(name) if name is not None else None
                    if p is None:
                        outs.append(Placement.replicate())
                    elif p.is_shard():
                        outs.append(Placement.shard(p.dim + 1))
                    elif p.is_partial():
                        outs.append(Placement.partial())
                    else:
                        outs.append(Placement.replicate())
            return ins, outs

        n_solves = 0
        full_body_compute = 0.0
        for i in edge_invars:
            v = eqn.invars[i]
            shape = tuple(v.aval.shape)
            numel = int(np.prod(shape)) if shape else 1
            if numel < self.world_size * 64:
                continue  # bias-sized: may ride along, never seeds
            is_xs = i >= n_xs_start
            is_carry = num_consts <= i < n_xs_start
            if not (is_carry or is_xs):
                continue  # hoisted consts ride along with carry seeds
            dim_range = range(1, len(shape)) if is_xs else range(len(shape))
            for outer_d in dim_range:
                if shape[outer_d] % self.world_size != 0 \
                        or shape[outer_d] < self.world_size:
                    continue
                if (i, outer_d) in covered:
                    continue  # already sharded by an earlier strategy
                if n_solves >= edconfig.scan_max_seed_solves:
                    break
                n_solves += 1
                body_d = outer_d - 1 if is_xs else outer_d
                res = solve_with_seed(in_names[i], body_d,
                                      carries_replicate=is_xs)
                if res is None:
                    continue
                full_body_compute = res[3]
                got = extract(res[0])
                if got is None:
                    continue
                ins, outs = got
                key = (tuple(repr(p) for p in ins),
                       tuple(repr(p) for p in outs))
                if key in seen_keys:
                    continue
                seen_keys.add(key)
                strategies.append((ins, outs, length * res[1],
                                   length * res[2]))
                for j, p in zip(edge_invars, ins):
                    if p.is_shard():
                        covered.add((j, p.dim))

        if not strategies:
            return None
        # full-compute proxy: the scan's work is length x the body's, far
        # more than its boundary bytes — without this the outer solver's
        # byte proxy under-prices replication and TP's intrinsic psum cost
        # would never be worth paying
        # same-basis replicate price (see _solve_body_pinned)
        compute = length * full_body_compute

        logger.debug("scan rule: %d whole-body strategies (body %d eqns, "
                    "length %d)", len(strategies), len(inner.jaxpr.eqns),
                    length)
        return {"space": None, "recombines": {},
                "strategies": strategies, "compute": compute}

    def _discover_cond(self, eqn):
        """Composite rule for `lax.cond`/`lax.switch`: every branch body is
        solved per seed input-dim; a whole-eqn strategy survives only when
        EVERY branch admits the identical boundary assignment (the branches
        share operands and output shapes, so a placement valid in one
        branch but not another would force an unpredictable runtime
        reshard).  Priced at the worst branch's collective cost — which
        branch runs is data-dependent.

        The reference never faces this: make_fx fully unrolls/flattens
        control flow so every op is visible
        (easydist/torch/compile.py:78-83); the TPU design keeps `cond`
        compiled (both branches live in the program) and constrains the
        outer operands, letting GSPMD propagate into the branches.
        """
        from easydist_tpu.metashard.metair import Placement

        branches = eqn.params.get("branches")
        if not branches:
            return None
        analyzed = []
        for br in branches:
            got = self._analyze_inner(br)
            if got is None:
                return None
            analyzed.append(got)
        operands = eqn.invars[1:]  # invar 0 is the branch index
        for inner_b, sub_b, _, _ in analyzed:
            if len(inner_b.jaxpr.invars) != len(operands):
                return None
        # operand indices each branch actually READS: cond unions the
        # branch closures, so every branch jaxpr is padded with the other
        # branches' captured weights as dead invars (a top-level invar
        # used anywhere must appear as a top-level eqn invar or outvar)
        used_sets = []
        for inner_b, _, _, _ in analyzed:
            used_vars = set()
            for be in inner_b.jaxpr.eqns:
                used_vars.update(bv for bv in be.invars
                                 if not isinstance(bv, jex_core.Literal))
            used_vars.update(bv for bv in inner_b.jaxpr.outvars
                             if not isinstance(bv, jex_core.Literal))
            used_sets.append({k for k, bv in enumerate(inner_b.jaxpr.invars)
                              if bv in used_vars})

        edge_invars = [i for i, v in enumerate(eqn.invars)
                       if not isinstance(v, jex_core.Literal)]
        strategies = []
        seen_keys = set()
        covered = set()
        n_solves = 0
        full_branch_compute = 0.0

        def branch_extract(inner_b, sub_b, var_p):
            in_names_b = [sub_b.names.name(v) for v in inner_b.jaxpr.invars]
            ins = []
            for i in edge_invars:
                if i == 0:  # branch index: scalar, always replicated
                    ins.append(Placement.replicate())
                    continue
                p = var_p.get(in_names_b[i - 1])
                if p is not None and p.is_shard():
                    shape = tuple(eqn.invars[i].aval.shape)
                    if shape[p.dim] % self.world_size != 0:
                        return None
                    ins.append(Placement.shard(p.dim))
                else:
                    ins.append(Placement.replicate())
            outs = []
            for v in inner_b.jaxpr.outvars:
                p = None if isinstance(v, jex_core.Literal) \
                    else var_p.get(sub_b.names.name(v))
                if p is not None and p.is_shard():
                    outs.append(Placement.shard(p.dim))
                elif p is not None and p.is_partial():
                    outs.append(Placement.partial())
                else:
                    outs.append(Placement.replicate())
            return ins, outs

        for j, v in enumerate(operands):
            shape = tuple(getattr(v.aval, "shape", ()))
            numel = int(np.prod(shape)) if shape else 1
            if isinstance(v, jex_core.Literal) \
                    or numel < self.world_size * 64:
                continue
            for d, size in enumerate(shape):
                if size % self.world_size != 0 or size < self.world_size:
                    continue
                if (j + 1, d) in covered:
                    continue
                if n_solves >= edconfig.scan_max_seed_solves:
                    break
                n_solves += 1
                per_branch = []
                for inner_b, sub_b, rules_b, shape_info_b in analyzed:
                    seed = sub_b.names.name(inner_b.jaxpr.invars[j])
                    res = self._solve_body_pinned(
                        inner_b, sub_b, rules_b, shape_info_b,
                        pins={seed: Placement.shard(d)})
                    if res is None:
                        break
                    got = branch_extract(inner_b, sub_b, res[0])
                    if got is None:
                        break
                    per_branch.append((got, res[1], res[2], res[3]))
                if len(per_branch) != len(analyzed):
                    continue
                # Join the per-branch boundaries treating operands a branch
                # never reads as don't-care: the body solver places a dead
                # invar arbitrarily, so demanding byte-identical boundary
                # keys rejects every seed whenever branches capture
                # different weights.  Disagreement on an operand some
                # branch actually reads still rejects the seed; an operand
                # no branch reads pins to replicate.
                joint_ins = []
                agree = True
                for pos, i in enumerate(edge_invars):
                    if i == 0:
                        joint_ins.append(Placement.replicate())
                        continue
                    picks_here = [per_branch[b][0][0][pos]
                                  for b in range(len(per_branch))
                                  if (i - 1) in used_sets[b]]
                    if len({repr(p) for p in picks_here}) > 1:
                        agree = False
                        break
                    joint_ins.append(picks_here[0] if picks_here
                                     else Placement.replicate())
                out_keys = {tuple(repr(p) for p in outs)
                            for (_, outs), _, _, _ in per_branch}
                if not agree or len(out_keys) != 1:
                    continue  # branches disagree on the boundary
                # fold the full-price compute only for solves that SURVIVED
                # the per-branch agreement check — a rejected solve's price
                # would skew the shard/replicate crossover the outer solver
                # compares against (ADVICE r5 #1)
                full_branch_compute = max(
                    [full_branch_compute] + [fc for _, _, _, fc in per_branch])
                ins, outs = joint_ins, per_branch[0][0][1]
                if all(p.is_replicate() for p in ins):
                    continue
                key = (tuple(repr(p) for p in ins), next(iter(out_keys)))
                if key in seen_keys:
                    continue
                seen_keys.add(key)
                comm = max(c for _, c, _, _ in per_branch)
                compute = max(c for _, _, c, _ in per_branch)
                strategies.append((ins, outs, comm, compute))
                for i, p in zip(edge_invars, ins):
                    if p.is_shard():
                        covered.add((i, p.dim))

        if not strategies:
            return None
        compute = full_branch_compute
        logger.debug("cond rule: %d whole-eqn strategies (%d branches)",
                    len(strategies), len(branches))
        return {"space": None, "recombines": {},
                "strategies": strategies, "compute": compute}

    def _discover_while(self, eqn):
        """Composite rule for `lax.while_loop`: the body is solved per
        carry seed with the carry threaded back to its init placeholder
        (scan's fixed-point treatment — a mismatched body output pays its
        priced in-loop reshard), and the COND jaxpr must then admit the
        chosen carry placements too (its own collectives are priced in —
        a `jnp.max(err) > tol` predicate over a sharded carry costs one
        small all-reduce per trip).  Trip count is unknown at trace time;
        `config.while_trip_estimate` scales the per-iteration price.
        Reference equivalent: full unrolling makes loops invisible
        (easydist/torch/compile.py:78-83); here the loop stays rolled.
        """
        from easydist_tpu.metashard.metair import Placement

        params = eqn.params
        n_cc = int(params.get("cond_nconsts", 0))
        n_bc = int(params.get("body_nconsts", 0))
        got_body = self._analyze_inner(params.get("body_jaxpr"))
        got_cond = self._analyze_inner(params.get("cond_jaxpr"))
        if got_body is None or got_cond is None:
            return None
        inner, sub, rules, shape_info = got_body
        cinner, csub, crules, cshape = got_cond

        body_invars = inner.jaxpr.invars  # [*body_consts, *carry]
        n_carry = len(body_invars) - n_bc
        if len(eqn.invars) != n_cc + n_bc + n_carry \
                or len(cinner.jaxpr.invars) != n_cc + n_carry:
            return None
        in_names = [sub.names.name(v) for v in body_invars]
        cond_in_names = [csub.names.name(v) for v in cinner.jaxpr.invars]
        out_names = [None if isinstance(v, jex_core.Literal)
                     else sub.names.name(v) for v in inner.jaxpr.outvars]
        carry_io = {}
        for k in range(n_carry):
            if out_names[k] is not None:
                carry_io[out_names[k]] = in_names[n_bc + k]

        edge_invars = [i for i, v in enumerate(eqn.invars)
                       if not isinstance(v, jex_core.Literal)]
        trips = float(edconfig.while_trip_estimate)
        strategies = []
        seen_keys = set()
        covered = set()
        n_solves = 0
        full_loop_compute = 0.0

        for k in range(n_carry):
            i = n_cc + n_bc + k  # absolute eqn invar index
            v = eqn.invars[i]
            shape = tuple(getattr(v.aval, "shape", ()))
            numel = int(np.prod(shape)) if shape else 1
            if isinstance(v, jex_core.Literal) \
                    or numel < self.world_size * 64:
                continue
            for d, size in enumerate(shape):
                if size % self.world_size != 0 or size < self.world_size:
                    continue
                if (i, d) in covered:
                    continue
                if n_solves >= edconfig.scan_max_seed_solves:
                    break
                n_solves += 1
                res = self._solve_body_pinned(
                    inner, sub, rules, shape_info,
                    pins={in_names[n_bc + k]: Placement.shard(d)},
                    state_io=carry_io)
                if res is None:
                    continue
                var_p, body_comm, body_compute, body_full = res
                full_loop_compute = body_full

                def carry_placement(kk):
                    p = var_p.get(in_names[n_bc + kk])
                    return p if p is not None else Placement.replicate()

                # the cond graph must run under these carry placements
                cond_pins = {}
                for kk in range(n_carry):
                    p = carry_placement(kk)
                    cond_pins[cond_in_names[n_cc + kk]] = (
                        Placement.shard(p.dim) if p.is_shard()
                        else Placement.replicate())
                # cond consts (loop bounds etc.) are reported replicated at
                # the emitted boundary (`ii < n_cc` below), so the
                # predicate solve must price them that way too — left
                # unpinned it could shard one and under-price the
                # crossover (ADVICE r5 #1; pricing only, never correctness)
                cres = self._solve_body_pinned(
                    cinner, csub, crules, cshape, pins=cond_pins,
                    replicate_names=tuple(cond_in_names[:n_cc]))
                if cres is None:
                    continue
                cond_comm = cres[1]

                ins = []
                ok = True
                for ii in edge_invars:
                    if ii < n_cc:  # cond consts: loop bounds etc, stay R
                        ins.append(Placement.replicate())
                        continue
                    if ii < n_cc + n_bc:
                        p = var_p.get(in_names[ii - n_cc])
                    else:
                        p = carry_placement(ii - n_cc - n_bc)
                    if p is not None and p.is_shard():
                        vshape = tuple(eqn.invars[ii].aval.shape)
                        if vshape[p.dim] % self.world_size != 0:
                            ok = False
                            break
                        ins.append(Placement.shard(p.dim))
                    else:
                        ins.append(Placement.replicate())
                if not ok or all(p.is_replicate() for p in ins):
                    continue
                # while outputs ARE the carry: same placements
                outs = [Placement.shard(carry_placement(kk).dim)
                        if carry_placement(kk).is_shard()
                        else Placement.replicate()
                        for kk in range(n_carry)]
                key = (tuple(repr(p) for p in ins),
                       tuple(repr(p) for p in outs))
                if key in seen_keys:
                    continue
                seen_keys.add(key)
                strategies.append((ins, outs,
                                   trips * (body_comm + cond_comm),
                                   trips * body_compute))
                for ii, p in zip(edge_invars, ins):
                    if p.is_shard():
                        covered.add((ii, p.dim))

        if not strategies:
            return None
        compute = trips * full_loop_compute
        logger.debug("while rule: %d whole-loop strategies (body %d eqns, "
                    "trip estimate %g)", len(strategies),
                    len(inner.jaxpr.eqns), trips)
        return {"space": None, "recombines": {},
                "strategies": strategies, "compute": compute}

    def _discover_shrunk(self, eqn, bind_fn, bind_params, prim_name):
        """Discovery on a size-reduced instance of the eqn, or None if the
        primitive rejects the shrunk shapes (shape-dependent params)."""
        import types

        cap = edconfig.discovery_hint_numel
        unit = max(self.world_size * edconfig.discovery_nshards, 8)
        sizes = sorted({d for v in list(eqn.invars) + list(eqn.outvars)
                        if hasattr(getattr(v, "aval", None), "shape")
                        for d in v.aval.shape if d > unit}, reverse=True)

        def shrunk_total(size_map):
            # inputs AND outputs: an output-dominated op (big matmul result)
            # must shrink too, or discovery materializes it full-size
            t = 0
            for v in list(eqn.invars) + list(eqn.outvars):
                if isinstance(v, jex_core.Literal) \
                        or not hasattr(getattr(v, "aval", None), "shape"):
                    continue
                t += int(np.prod([size_map.get(d, d) for d in v.aval.shape]))
            return t

        size_map = {}
        # halve the largest mapped sizes (to a multiple of `unit`) until the
        # inputs fit the hint budget
        for _ in range(64):
            if shrunk_total(size_map) <= cap:
                break
            grew = False
            for d in sizes:
                cur = size_map.get(d, d)
                nxt = max((cur // 2) // unit * unit, unit)
                if nxt < cur:
                    size_map[d] = nxt
                    grew = True
                    break
            if not grew:
                return None
        if not size_map:
            return None

        with jax.default_device(
                jax.local_devices(backend="cpu")[0]
                if edconfig.discovery_on_cpu else jax.devices()[0]):
            invals = []
            for v in eqn.invars:
                if isinstance(v, jex_core.Literal):
                    invals.append(v.val)
                    continue
                aval = v.aval
                shape = tuple(size_map.get(d, d) for d in aval.shape)
                invals.append(_materialize(
                    types.SimpleNamespace(shape=shape, dtype=aval.dtype),
                    self._next_key()))
            try:
                bind_fn(*invals, **bind_params)  # params consistent?
            except Exception:
                return None
            op = MetaOp(bind_fn, tuple(invals), kwargs=bind_params,
                        name=prim_name)
            try:
                space, recombines = op.discover(
                    prompt=self.prompts.get(prim_name))
            except Exception:
                return None
        if prim_name not in self.prompts and space.max_group() > 0:
            self.prompts[prim_name] = space
        return {"space": space, "recombines": recombines}


# ops through which a partial-sum placement propagates linearly: f(sum_i x_i)
# == sum_i f(x_i) when every other operand is replicated.  Used only inside
# composite (jax.checkpoint body) solves, where a partial may travel to the
# composite boundary and become a reduce recombine — e.g. a bias gradient's
# reduce_sum inside a differentiated remat body.
_PARTIAL_LINEAR_1IN = {"reshape", "transpose", "convert_element_type",
                       "squeeze", "expand_dims", "broadcast_in_dim", "neg",
                       "rev", "slice", "reduce_sum", "copy"}
_PARTIAL_LINEAR_2IN = {"mul", "div", "dot_general"}


def _inject_partial_propagation(graph, world_size: int) -> None:
    # NOTE: mul-by-LITERAL (n_in == 1) deliberately gets no P-passthrough.
    # Scaling by a constant is linear, but injecting it lets P ride into
    # loss-scale and optimizer-update chains where deferral is byte-neutral
    # at best — measured: a worse near-tie on the dp MLP (liveness +56%)
    # and 37 extra all-to-alls on the remat-policy GPT twin.  Revisit once
    # fence costs are priced inside the ILP rather than post-hoc.
    from easydist_tpu.metashard.metair import NodeStrategy, Placement

    par = Placement.partial()
    rep = Placement.replicate()
    for node in graph.ops:
        base = node.strategy_pool(world_size)  # builds _pool_cache
        if not base or node._pool_cache is None:
            continue
        template = base[0]
        n_in = len(template.in_placements)
        n_out = len(template.out_placements)
        extras = []
        if node.op_key in _PARTIAL_LINEAR_1IN and n_in >= 1:
            # partial rides the first (data) operand; any trailing operands
            # must be replicated
            extras.append(NodeStrategy([par] + [rep] * (n_in - 1),
                                       [par] * n_out))
        elif node.op_key in _PARTIAL_LINEAR_2IN and n_in == 2:
            extras.append(NodeStrategy([par, rep], [par] * n_out))
            if node.op_key != "div":  # div is linear in the numerator only
                extras.append(NodeStrategy([rep, par], [par] * n_out))
        node._pool_cache = node._pool_cache + extras
