"""PyTorch frontend: torch modules -> jax functions -> the same auto-parallel
pipeline, executing GPU-free through XLA.

The reference's torch frontend (easydist/torch/, ~15k LoC) traces
model+optimizer into one fx graph and runs it over NCCL; per the north star
(BASELINE.json) this frontend instead lowers `torch.export`'s aten graph to
jax, reuses the jax solver/emission stack unchanged, and replaces the
CUDA/NCCL runtime entirely.
"""

from .convert import torch_module_to_jax  # noqa: F401
from .api import (easydist_compile_torch, make_torch_pp_train_step,  # noqa: F401
                  make_torch_train_step)
