"""User API for the torch frontend.

`easydist_compile_torch(module, example_args)` — auto-parallel inference on
the converted module.  `make_torch_train_step(module, loss, ...)` — full
training: the converted forward runs under jax autodiff with our Adam/SGD,
and the whole step goes through `easydist_compile` (reference equivalent:
`@easydist_compile()(train_step)(model, opt, ...)`, torch/api.py:227 — there
via fx-tracing torch autograd+optimizer; here via jax transforms on the
converted function, which is the TPU-native route to the same contract).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from easydist_tpu.jaxfront.api import easydist_compile
from easydist_tpu.models.optim import (adagrad_init, adagrad_update,
                                       adam_init, adam_update, rmsprop_init,
                                       rmsprop_update, sgd_init, sgd_update)
from .convert import torch_module_to_jax


def easydist_compile_torch(module, example_args, mesh=None, **kwargs):
    """Auto-parallelized inference callable for a torch module.

    Returns (compiled_fn, params): compiled_fn(params, *jax_inputs) runs the
    sharded forward; params is the converted jax param dict (update/replace
    leaves to load new weights)."""
    fn, params = torch_module_to_jax(module, example_args)
    compiled = easydist_compile(fn, mesh=mesh, state_io={}, **kwargs)
    return compiled, params


def _translate_torch_optimizer(optimizer, module):
    """torch.optim instance -> (kind, hyperparams, state translator)
    (reference: the user's own torch optimizer captured by fx tracing,
    torch/compile.py:25-95; here translated into the equivalent jax update).
    Kinds: Adam, AdamW, SGD, RMSprop, Adagrad.

    Multiple param groups translate into per-parameter lr/weight_decay (and
    for Adam, betas) TREES (models/optim.py broadcasts them leafwise); a
    param absent from every group gets lr 0 (torch would never step it).
    Other hyperparameters must be uniform across groups.
    """
    name_of = {id(p): n for n, p in module.named_parameters()}
    groups = optimizer.param_groups
    kind = type(optimizer).__name__.lower()
    if kind not in ("adam", "adamw", "sgd", "rmsprop", "adagrad"):
        raise NotImplementedError(
            f"torch optimizer {type(optimizer).__name__} not supported "
            f"(Adam, AdamW, SGD, RMSprop and Adagrad are)")

    def uniform(key, default=None):
        vals = {repr(g.get(key, default)) for g in groups}
        if len(vals) != 1:
            raise NotImplementedError(
                f"per-group {key} not supported (groups have {vals})")
        return groups[0].get(key, default)

    # per-param trees over every named parameter; group membership decides
    lr_tree = {n: 0.0 for n in name_of.values()}
    wd_tree = {n: 0.0 for n in name_of.values()}
    for g in groups:
        for p in g["params"]:
            qual = name_of.get(id(p))
            if qual is None:
                raise ValueError(
                    "optimizer param not found among module parameters")
            lr_tree[qual] = float(g["lr"])
            wd_tree[qual] = float(g.get("weight_decay", 0.0))
    multi = len(groups) > 1
    lr_h = lr_tree if multi else groups[0]["lr"]
    wd_h = wd_tree if multi else groups[0].get("weight_decay", 0.0)

    if kind in ("adam", "adamw"):
        if uniform("amsgrad", False) or uniform("maximize", False):
            raise NotImplementedError("Adam amsgrad/maximize not supported")
        betas = {repr(g["betas"]) for g in groups}
        if len(betas) == 1:
            b1, b2 = groups[0]["betas"]
        else:  # per-group betas -> per-leaf trees (default where unlisted)
            b1 = {n: 0.9 for n in name_of.values()}
            b2 = {n: 0.999 for n in name_of.values()}
            for g in groups:
                for p in g["params"]:
                    qual = name_of[id(p)]
                    b1[qual], b2[qual] = map(float, g["betas"])
        hyper = {"lr": lr_h, "b1": b1, "b2": b2, "eps": uniform("eps"),
                 "weight_decay": wd_h, "decoupled": kind == "adamw"}
    elif kind == "rmsprop":
        hyper = {"lr": lr_h, "alpha": float(uniform("alpha", 0.99)),
                 "eps": float(uniform("eps", 1e-8)),
                 "momentum": float(uniform("momentum", 0.0) or 0.0),
                 "centered": bool(uniform("centered", False)),
                 "weight_decay": wd_h}
    elif kind == "adagrad":
        adagrad_iav = float(uniform("initial_accumulator_value", 0.0))
        hyper = {"lr": lr_h, "lr_decay": float(uniform("lr_decay", 0.0)),
                 "eps": float(uniform("eps", 1e-10)),
                 "weight_decay": wd_h,
                 "initial_accumulator_value": adagrad_iav}
    else:  # sgd
        hyper = {"lr": lr_h,
                 "momentum": float(uniform("momentum", 0.0) or 0.0),
                 "nesterov": bool(uniform("nesterov", False)),
                 "weight_decay": wd_h}

    def translate_state(params0):
        """Carry over a warm optimizer's buffers: exp_avg/exp_avg_sq/step
        (adam), momentum buffers (sgd), square_avg/grad_avg (rmsprop),
        sum/step (adagrad)."""
        import jax.numpy as jnp
        import numpy as np

        def t(tensor):
            return jnp.array(tensor.detach().numpy())

        if kind == "sgd":
            if not hyper["momentum"]:
                return None
            opt = sgd_init(dict(params0))
            for p, st in optimizer.state.items():
                qual = name_of.get(id(p))
                if qual is None or st.get("momentum_buffer") is None:
                    continue
                opt["buf"][qual] = t(st["momentum_buffer"])
            return opt
        if kind == "rmsprop":
            opt = rmsprop_init(dict(params0), momentum=hyper["momentum"],
                               centered=hyper["centered"])
            for p, st in optimizer.state.items():
                qual = name_of.get(id(p))
                if qual is None or "square_avg" not in st:
                    continue
                opt["sq"][qual] = t(st["square_avg"])
                if "buf" in opt and st.get("momentum_buffer") is not None:
                    opt["buf"][qual] = t(st["momentum_buffer"])
                if "gavg" in opt and st.get("grad_avg") is not None:
                    opt["gavg"][qual] = t(st["grad_avg"])
            return opt
        if kind == "adagrad":
            # hyper's copy is popped by _stateful_opt_fns before init runs
            opt = adagrad_init(dict(params0),
                               initial_accumulator_value=adagrad_iav)
            step_count = 0
            for p, st in optimizer.state.items():
                qual = name_of.get(id(p))
                if qual is None or "sum" not in st:
                    continue
                opt["sum"][qual] = t(st["sum"])
                step_count = int(st["step"])
            opt["count"] = jnp.asarray(np.int32(step_count))
            return opt
        opt = adam_init(dict(params0))
        step_count = 0
        for p, st in optimizer.state.items():
            qual = name_of.get(id(p))
            if qual is None or "exp_avg" not in st:
                continue
            opt["mu"][qual] = t(st["exp_avg"])
            opt["nu"][qual] = t(st["exp_avg_sq"])
            step_count = int(st["step"])
        opt["count"] = jnp.asarray(np.int32(step_count))
        return opt

    # adamw rides the adam code path (decoupled flag in hyper)
    return ("adam" if kind == "adamw" else kind), hyper, translate_state


def _stateful_opt_fns(optimizer, hyper):
    """(init(params), update(params, grads, state, lr, **hyper)) for the
    stateful optimizer kinds; None for sgd (handled separately — its
    momentum-free form is stateless)."""
    if optimizer == "adam":
        return adam_init, adam_update
    if optimizer == "rmsprop":
        mom = hyper.get("momentum", 0.0)
        cen = hyper.get("centered", False)
        return (lambda p: rmsprop_init(p, momentum=mom, centered=cen),
                rmsprop_update)
    if optimizer == "adagrad":
        iav = hyper.pop("initial_accumulator_value", 0.0)
        return (lambda p: adagrad_init(p, initial_accumulator_value=iav),
                adagrad_update)
    return None


def make_torch_train_step(module, example_args, loss_fn: Callable,
                          optimizer="adam", lr: float = 1e-3,
                          mesh=None, parallel_mode: str = "auto",
                          train: Optional[bool] = None, **kwargs):
    """Build an auto-parallelized train step from a torch module.

    loss_fn(outputs, *targets) -> scalar jax loss.
    optimizer: "adam" / "sgd" / "rmsprop" / "adagrad", or a torch.optim
    Adam/AdamW/SGD/RMSprop/Adagrad INSTANCE built on this module — its
    hyperparameters (incl. per-group lr/weight_decay/betas) and warm
    buffers (exp_avg/exp_avg_sq/step, momentum, square_avg, sum) are
    translated into the jax update.
    parallel_mode: "auto" (solver-chosen SPMD, the default) or the manual
    modes "ddp" / "zero2" / "zero3" (reference torch/api.py parallel_mode
    kwarg, compile_dp.py) — manual modes shard the batch over the mesh's
    first axis explicitly.
    train: False (default) exports eval-mode semantics regardless of the
    module's mode flag (torch modules are constructed in training mode, so
    inferring from module.training would silently change every caller).
    train=True exports training-mode semantics (dropout active, batch-norm
    batch stats + running stat updates) and the step takes an rng:
      compiled_step(state, rng, inputs, *targets) -> (new_state, loss)
      state = ((trainable, buffers), opt_state)
    In eval-export mode (train=False):
      compiled_step(state, inputs, *targets) -> (new_state, loss)
      state = (params, opt_state) for adam, params for sgd
    """
    train = bool(train)

    torch_opt = None
    if not isinstance(optimizer, str):
        torch_opt = optimizer
        optimizer, hyper, translate_state = _translate_torch_optimizer(
            torch_opt, module)
        lr = hyper.pop("lr")
    else:
        hyper, translate_state = {}, None

    if train:
        return _make_train_mode_step(module, example_args, loss_fn,
                                     optimizer, lr, hyper, translate_state,
                                     mesh, parallel_mode=parallel_mode,
                                     **kwargs)

    fwd, params0 = torch_module_to_jax(module, example_args)
    # buffers (batch-norm running stats etc.) are not weights: keep them out
    # of autodiff and the optimizer update — eval-mode BN differentiates
    # through its running stats, and "training" them corrupts inference
    buffer_names = fwd.buffer_names
    trainable0 = {k: v for k, v in params0.items() if k not in buffer_names}

    if parallel_mode != "auto":
        from easydist_tpu.jaxfront.mesh import get_device_mesh
        from easydist_tpu.parallel import ddp_step, zero2_step, zero3_step

        mesh = mesh or get_device_mesh()
        axis = mesh.axis_names[0]

        def objective(p, inputs, *targets):
            return loss_fn(fwd(p, inputs), *targets)

        # manual modes carry their own optimizer: ddp is SGD, zero2/3 are
        # Adam — reject a contradictory `optimizer` rather than silently
        # training with a different one
        if parallel_mode == "ddp" and optimizer != "sgd":
            raise ValueError("parallel_mode='ddp' trains with SGD; pass "
                             "optimizer='sgd' (or use parallel_mode='auto')")
        if parallel_mode in ("zero2", "zero3") and optimizer != "adam":
            raise ValueError(f"parallel_mode={parallel_mode!r} trains with "
                             "Adam; pass optimizer='adam'")
        if parallel_mode == "ddp":
            step = ddp_step(objective, mesh, axis=axis, lr=lr)
            return step, lambda: params0
        if parallel_mode == "zero2":
            step, init_opt = zero2_step(objective, mesh, axis=axis, lr=lr)
            import jax.numpy as _jnp

            return step, lambda: (params0, init_opt(params0),
                                  _jnp.zeros((), _jnp.int32))
        if parallel_mode == "zero3":
            step, init_state3 = zero3_step(objective, mesh, axis=axis, lr=lr)
            return step, lambda: init_state3(params0)
        raise ValueError(f"unknown parallel_mode {parallel_mode!r}")

    opt_fns = _stateful_opt_fns(optimizer, hyper)
    if opt_fns is not None:
        opt_init, opt_update = opt_fns

        def init_state():
            opt = translate_state(trainable0) if translate_state else None
            return (params0,
                    opt if opt is not None else opt_init(trainable0))

        def step(state, inputs, *targets):
            params, opt = state
            trainable = {k: v for k, v in params.items()
                         if k not in buffer_names}
            buffers = {k: v for k, v in params.items() if k in buffer_names}

            def objective(tp):
                return loss_fn(fwd({**tp, **buffers}, inputs), *targets)

            loss, grads = jax.value_and_grad(objective)(trainable)
            new_tp, new_opt = opt_update(trainable, grads, opt, lr=lr,
                                         **hyper)
            return ({**new_tp, **buffers}, new_opt), loss
    elif optimizer == "sgd" and hyper.get("momentum"):
        def init_state():
            opt = translate_state(trainable0) if translate_state else None
            return (params0, opt if opt is not None else sgd_init(trainable0))

        def step(state, inputs, *targets):
            params, opt = state
            trainable = {k: v for k, v in params.items()
                         if k not in buffer_names}
            buffers = {k: v for k, v in params.items() if k in buffer_names}

            def objective(tp):
                return loss_fn(fwd({**tp, **buffers}, inputs), *targets)

            loss, grads = jax.value_and_grad(objective)(trainable)
            new_tp, new_opt = sgd_update(trainable, grads, lr=lr,
                                         state=opt, **hyper)
            return ({**new_tp, **buffers}, new_opt), loss
    elif optimizer == "sgd":
        def init_state():
            return params0

        def step(params, inputs, *targets):
            trainable = {k: v for k, v in params.items()
                         if k not in buffer_names}
            buffers = {k: v for k, v in params.items() if k in buffer_names}

            def objective(tp):
                return loss_fn(fwd({**tp, **buffers}, inputs), *targets)

            loss, grads = jax.value_and_grad(objective)(trainable)
            return {**sgd_update(trainable, grads, lr=lr, **hyper),
                    **buffers}, loss
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")

    return easydist_compile(step, mesh=mesh, **kwargs), init_state


def _make_train_mode_step(module, example_args, loss_fn, optimizer, lr,
                          hyper, translate_state, mesh,
                          parallel_mode: str = "auto", **kwargs):
    """Training-mode export: dropout rng threading + batch-norm running
    stats in the state.  state = ((trainable, buffers), opt_state);
    step(state, rng, inputs, *targets) -> (state, loss).

    parallel_mode "ddp"/"zero2"/"zero3" (reference torch/api.py +
    compile_dp.py) is expressed TPU-style: one jit with pinned GSPMD
    placements instead of per-rank NCCL programs — batch sharded over the
    mesh's first axis (GSPMD inserts the grad all-reduce), optimizer
    moments flat-sharded over it for zero2, parameters too for zero3
    (GSPMD all-gathers weights at use — the ZeRO-3 gather).  Batch-norm
    statistics stay GLOBAL-batch exact (single-process eager semantics;
    torch DDP's unsynced per-rank BN is weaker)."""
    fwd, params0 = torch_module_to_jax(module, example_args, train=True)
    buffer_names = fwd.buffer_names
    trainable0 = {k: v for k, v in params0.items()
                  if k not in buffer_names}
    buffers0 = {k: v for k, v in params0.items() if k in buffer_names}

    opt_fns = _stateful_opt_fns(optimizer, hyper)
    if opt_fns is not None:
        opt_init, opt_update = opt_fns

        def init_state():
            opt = translate_state(trainable0) if translate_state else None
            return ((trainable0, buffers0),
                    opt if opt is not None else opt_init(trainable0))

        def step(state, rng, inputs, *targets):
            (trainable, buffers), opt = state

            def objective(tp):
                out, new_buf = fwd({**tp, **buffers}, rng, inputs)
                return loss_fn(out, *targets), new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                objective, has_aux=True)(trainable)
            new_tp, new_opt = opt_update(trainable, grads, opt, lr=lr,
                                         **hyper)
            return ((new_tp, {**buffers, **new_buf}), new_opt), loss
    elif optimizer == "sgd" and hyper.get("momentum"):
        def init_state():
            opt = translate_state(trainable0) if translate_state else None
            return ((trainable0, buffers0),
                    opt if opt is not None else sgd_init(trainable0))

        def step(state, rng, inputs, *targets):
            (trainable, buffers), opt = state

            def objective(tp):
                out, new_buf = fwd({**tp, **buffers}, rng, inputs)
                return loss_fn(out, *targets), new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                objective, has_aux=True)(trainable)
            new_tp, new_opt = sgd_update(trainable, grads, lr=lr,
                                         state=opt, **hyper)
            return ((new_tp, {**buffers, **new_buf}), new_opt), loss
    elif optimizer == "sgd":
        def init_state():
            return ((trainable0, buffers0), None)

        def step(state, rng, inputs, *targets):
            (trainable, buffers), _ = state

            def objective(tp):
                out, new_buf = fwd({**tp, **buffers}, rng, inputs)
                return loss_fn(out, *targets), new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                objective, has_aux=True)(trainable)
            new_tp = sgd_update(trainable, grads, lr=lr, **hyper)
            return ((new_tp, {**buffers, **new_buf}), None), loss
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")

    if parallel_mode == "auto":
        return easydist_compile(step, mesh=mesh, **kwargs), init_state
    if parallel_mode not in ("ddp", "zero2", "zero3"):
        raise ValueError(f"unknown parallel_mode {parallel_mode!r}")

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from easydist_tpu.jaxfront.mesh import get_device_mesh

    mesh = mesh or get_device_mesh()
    if mesh is None:
        raise ValueError(f"parallel_mode={parallel_mode!r} needs a mesh")
    axis = mesh.axis_names[0]
    n_dp = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def _flat_place(tree):
        """dim-0 flat sharding over the dp axis when divisible (the ZeRO
        placement); indivisible/scalar leaves stay replicated."""
        return jax.tree_util.tree_map(
            lambda v: NamedSharding(mesh, P(axis))
            if getattr(v, "ndim", 0) > 0 and v.shape[0] % n_dp == 0
            else repl, tree)

    def _state_shardings(state):
        (tp, buf), opt = state
        tp_s = _flat_place(tp) if parallel_mode == "zero3" \
            else jax.tree_util.tree_map(lambda _: repl, tp)
        buf_s = jax.tree_util.tree_map(lambda _: repl, buf)
        opt_s = _flat_place(opt) if parallel_mode in ("zero2", "zero3") \
            else jax.tree_util.tree_map(lambda _: repl, opt)
        return ((tp_s, buf_s), opt_s)

    def _shard_batch(t):
        return jax.tree_util.tree_map(
            lambda v: jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(axis)))
            if getattr(v, "ndim", 0) > 0 and v.shape[0] % n_dp == 0 else v,
            t)

    def manual_step(state, rng, inputs, *targets):
        new_state, loss = step(state, rng, _shard_batch(inputs),
                               *_shard_batch(targets))
        new_state = jax.lax.with_sharding_constraint(
            new_state, _state_shardings(new_state))
        return new_state, loss

    unsupported = set(kwargs) - {"donate_state"}
    if unsupported:
        raise ValueError(
            f"{sorted(unsupported)} are not supported with "
            f"parallel_mode={parallel_mode!r} train-mode export (the "
            f"manual modes bypass easydist_compile; only donate_state "
            f"applies)")
    from easydist_tpu import config as edconfig

    donate_state = kwargs.get("donate_state")
    if donate_state is None:  # same default resolution as the auto path
        donate_state = edconfig.enable_donation
    donate = (0,) if donate_state else ()
    jitted = jax.jit(manual_step, donate_argnums=donate)

    def placed_init_state():
        state = init_state()
        return jax.device_put(state, _state_shardings(state))

    return jitted, placed_init_state


def make_torch_pp_train_step(module, example_args, loss_fn: Callable,
                             mesh, pp_stages: int,
                             n_microbatches: Optional[int] = None,
                             lr: Optional[float] = None,
                             optimizer: str = "adam",
                             schedule: str = "gpipe", tp_axes=None,
                             train: bool = False, pp_axis: str = "pp"):
    """Pipeline-parallel training for a torch module — the torch frontend
    entry to the hybrid auto-PP x SPMD compile (reference:
    easydist/torch/experimental/pp/api.py:13-105, where per-rank processes
    run ScheduleGPipe/DAPPLE over NCCL; here the converted module is
    auto-split into stages of ONE fully-manual SPMD program,
    jaxfront/pp_compile.py).

    Returns (compiled, params0):
        state = compiled.init_state(params0, inputs, *targets)
        state, loss = compiled(state, inputs, *targets)

    loss_fn(outputs, *targets) -> scalar jax loss (mean reduction).
    train=True exports training-mode semantics; modules with stateful
    buffers (batch-norm running stats) or active dropout are rejected —
    their updates do not thread through pipeline stages yet (use
    parallel_mode='auto' in make_torch_train_step for those).
    optimizer: 'adam' or 'sgd' (the pp path runs its optimizer on the
    packed stage rows; torch.optim instances with per-group
    hyperparameters do not map onto that flat representation).
    pp_axis: name of the mesh axis stages are laid out over (default
    'pp'); every other mesh axis is a batch sibling unless listed in
    tp_axes.
    """
    if not isinstance(optimizer, str):
        raise NotImplementedError(
            "torch.optim instances are not supported with pp_stages: the "
            "pipeline optimizer runs on packed flat stage rows, which "
            "per-parameter-group hyperparameters cannot address; pass "
            "optimizer='adam'/'sgd' + lr=")
    # validate the mesh axes up front (ADVICE r5 #5): a pipeline axis under
    # another name used to fail only later in _build's mesh check, AFTER
    # the batch-divisibility message had been computed with a wrong
    # sibling count
    if pp_axis not in mesh.axis_names:
        raise ValueError(
            f"pp_axis {pp_axis!r} is not a mesh axis (mesh has "
            f"{tuple(mesh.axis_names)}); pass pp_axis= matching your "
            f"mesh's pipeline axis name")
    for a in (tp_axes or ()):
        if a not in mesh.axis_names:
            raise ValueError(
                f"tp_axes entry {a!r} is not a mesh axis (mesh has "
                f"{tuple(mesh.axis_names)})")
        if a == pp_axis:
            raise ValueError(
                f"tp_axes entry {a!r} collides with pp_axis {pp_axis!r}")
    # torch.export bakes concrete sizes into view/reshape params, and the
    # pipeline replays stages at BATCH-LOCAL microbatch shape — so the
    # module must be exported at exactly that shape
    M = n_microbatches or pp_stages * 2
    batch_axes = [a for a in mesh.axis_names
                  if a != pp_axis and a not in (tp_axes or ())]
    import math as _math

    n_batch = _math.prod(int(mesh.shape[a]) for a in batch_axes)
    div = M * n_batch

    def _shrink(x):
        if x.shape[0] % div != 0:
            raise ValueError(
                f"example batch dim {x.shape[0]} not divisible by "
                f"n_microbatches*batch-siblings = {M}*{n_batch}")
        return x[: x.shape[0] // div]

    local_args = tuple(_shrink(a) for a in example_args)
    fwd, params0 = torch_module_to_jax(module, local_args, train=train)
    if getattr(fwd, "mutated_buffer_names", None):
        raise NotImplementedError(
            "modules that MUTATE buffers (batch-norm running stats) "
            "cannot pipeline yet — buffer updates do not thread through "
            "stage boundaries; use make_torch_train_step(..., "
            "parallel_mode='auto').  Constant buffers (masks) are fine.")
    # buffers are not weights: float buffers (eval-mode BN running stats)
    # must never reach the pipeline optimizer, so close over them instead
    # of handing them to pp_compile as trainable leaves
    buffers0 = {k: v for k, v in params0.items() if k in fwd.buffer_names}
    params0 = {k: v for k, v in params0.items()
               if k not in fwd.buffer_names}
    raw_fwd = fwd

    if buffers0:
        if train:
            def fwd(p, rng, inputs):  # noqa: F811
                return raw_fwd({**p, **buffers0}, rng, inputs)
        else:
            def fwd(p, inputs):  # noqa: F811
                return raw_fwd({**p, **buffers0}, inputs)
        fwd.buffer_names = raw_fwd.buffer_names
        fwd.aten_ops = raw_fwd.aten_ops
        fwd.stochastic_ops = raw_fwd.stochastic_ops

    if train:
        import jax as _jax

        _fixed_rng = _jax.random.PRNGKey(0)

        def loss(params, inputs, *targets):
            out, _ = fwd(params, _fixed_rng, inputs)
            return loss_fn(out, *targets)

        # a fixed rng would silently freeze dropout masks across steps
        # (stochastic_ops also catches sdpa's argument-carried dropout_p,
        # which no op-NAME check can see)
        if getattr(fwd, "stochastic_ops", ()):
            raise NotImplementedError(
                f"stochastic ops {sorted(fwd.stochastic_ops)} cannot "
                f"pipeline yet (the step-invariant rng would freeze their "
                f"masks); export with p=0 or use parallel_mode='auto'")
    else:
        def loss(params, inputs, *targets):
            return loss_fn(fwd(params, inputs), *targets)

    compiled = easydist_compile(loss, mesh=mesh, pp_stages=pp_stages,
                                n_microbatches=M, lr=lr,
                                optimizer=optimizer, schedule=schedule,
                                tp_axes=tp_axes, pp_axis=pp_axis)
    return compiled, params0
