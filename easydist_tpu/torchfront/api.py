"""User API for the torch frontend.

`easydist_compile_torch(module, example_args)` — auto-parallel inference on
the converted module.  `make_torch_train_step(module, loss, ...)` — full
training: the converted forward runs under jax autodiff with our Adam/SGD,
and the whole step goes through `easydist_compile` (reference equivalent:
`@easydist_compile()(train_step)(model, opt, ...)`, torch/api.py:227 — there
via fx-tracing torch autograd+optimizer; here via jax transforms on the
converted function, which is the TPU-native route to the same contract).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from easydist_tpu.jaxfront.api import easydist_compile
from easydist_tpu.models.optim import adam_init, adam_update, sgd_update
from .convert import torch_module_to_jax


def easydist_compile_torch(module, example_args, mesh=None, **kwargs):
    """Auto-parallelized inference callable for a torch module.

    Returns (compiled_fn, params): compiled_fn(params, *jax_inputs) runs the
    sharded forward; params is the converted jax param dict (update/replace
    leaves to load new weights)."""
    fn, params = torch_module_to_jax(module, example_args)
    compiled = easydist_compile(fn, mesh=mesh, state_io={}, **kwargs)
    return compiled, params


def make_torch_train_step(module, example_args, loss_fn: Callable,
                          optimizer: str = "adam", lr: float = 1e-3,
                          mesh=None, parallel_mode: str = "auto", **kwargs):
    """Build an auto-parallelized train step from a torch module.

    loss_fn(outputs, *targets) -> scalar jax loss.
    parallel_mode: "auto" (solver-chosen SPMD, the default) or the manual
    modes "ddp" / "zero2" / "zero3" (reference torch/api.py parallel_mode
    kwarg, compile_dp.py) — manual modes shard the batch over the mesh's
    first axis explicitly.
    Returns (compiled_step, init_state):
      state = (params, opt_state) for adam, params for sgd
      compiled_step(state, inputs, *targets) -> (new_state, loss)
    """
    fwd, params0 = torch_module_to_jax(module, example_args)

    if parallel_mode != "auto":
        from easydist_tpu.jaxfront.mesh import get_device_mesh
        from easydist_tpu.parallel import ddp_step, zero2_step, zero3_step

        mesh = mesh or get_device_mesh()
        axis = mesh.axis_names[0]

        def objective(p, inputs, *targets):
            return loss_fn(fwd(p, inputs), *targets)

        # manual modes carry their own optimizer: ddp is SGD, zero2/3 are
        # Adam — reject a contradictory `optimizer` rather than silently
        # training with a different one
        if parallel_mode == "ddp" and optimizer != "sgd":
            raise ValueError("parallel_mode='ddp' trains with SGD; pass "
                             "optimizer='sgd' (or use parallel_mode='auto')")
        if parallel_mode in ("zero2", "zero3") and optimizer != "adam":
            raise ValueError(f"parallel_mode={parallel_mode!r} trains with "
                             "Adam; pass optimizer='adam'")
        if parallel_mode == "ddp":
            step = ddp_step(objective, mesh, axis=axis, lr=lr)
            return step, lambda: params0
        if parallel_mode == "zero2":
            step, init_opt = zero2_step(objective, mesh, axis=axis, lr=lr)
            import jax.numpy as _jnp

            return step, lambda: (params0, init_opt(params0),
                                  _jnp.zeros((), _jnp.int32))
        if parallel_mode == "zero3":
            step, init_state3 = zero3_step(objective, mesh, axis=axis, lr=lr)
            return step, lambda: init_state3(params0)
        raise ValueError(f"unknown parallel_mode {parallel_mode!r}")

    if optimizer == "adam":
        def init_state():
            return (params0, adam_init(params0))

        def step(state, inputs, *targets):
            params, opt = state

            def objective(p):
                return loss_fn(fwd(p, inputs), *targets)

            loss, grads = jax.value_and_grad(objective)(params)
            new_params, new_opt = adam_update(params, grads, opt, lr=lr)
            return (new_params, new_opt), loss
    elif optimizer == "sgd":
        def init_state():
            return params0

        def step(params, inputs, *targets):
            def objective(p):
                return loss_fn(fwd(p, inputs), *targets)

            loss, grads = jax.value_and_grad(objective)(params)
            return sgd_update(params, grads, lr=lr), loss
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")

    return easydist_compile(step, mesh=mesh, **kwargs), init_state
