"""aten graph -> jax function conversion.

`torch.export.export` gives a functionalized aten-level fx graph whose
placeholders are (params..., buffers..., user inputs...).  Each aten op maps
to a jax implementation through the registry below (the conversion analog of
the reference's DTensor prop-rule bank, torch/spmd_prop_rule.py — but
producing executable jax instead of sharding metadata; sharding then comes
from our own discovery on the jax side).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

_ATEN: Dict[str, Callable] = {}


def register_aten(*names):
    def deco(fn):
        for n in names:
            _ATEN[n] = fn
        return fn

    return deco


class UnsupportedAtenOp(NotImplementedError):
    pass


# Per-conversion PRNG context for training-mode stochastic ops (dropout).
# Set by run_graph for the duration of one forward; each stochastic op
# folds a fresh per-site counter into the step key, so masks are
# deterministic per (rng, op position) and differ across ops.
_RNG_STATE: List = [None, 0]


def _set_rng(key):
    _RNG_STATE[0] = key
    _RNG_STATE[1] = 0


def _next_rng(hint: str = None):
    if _RNG_STATE[0] is None:
        raise UnsupportedAtenOp(hint or (
            "training-mode dropout needs an rng: convert with "
            "torch_module_to_jax(..., train=True) and call fn(params, rng, "
            "*inputs)"))
    key = jax.random.fold_in(_RNG_STATE[0], _RNG_STATE[1])
    _RNG_STATE[1] += 1
    return key


# ------------------------------------------------------------ conversions

@register_aten("aten.linear.default")
def _linear(x, w, b=None):
    out = x @ w.T
    return out + b if b is not None else out


@register_aten("aten.mm.default", "aten.matmul.default", "aten.bmm.default")
def _matmul(a, b):
    return a @ b


@register_aten("aten.addmm.default")
def _addmm(bias, a, b):
    return bias + a @ b


@register_aten("aten.relu.default", "aten.relu_.default")
def _relu(x):
    return jax.nn.relu(x)


@register_aten("aten.gelu.default")
def _gelu(x, approximate="none"):
    return jax.nn.gelu(x, approximate=(approximate == "tanh"))


@register_aten("aten.silu.default")
def _silu(x):
    return jax.nn.silu(x)


@register_aten("aten.sigmoid.default")
def _sigmoid(x):
    return jax.nn.sigmoid(x)


@register_aten("aten.tanh.default")
def _tanh(x):
    return jnp.tanh(x)


@register_aten("aten.add.Tensor", "aten.add_.Tensor")
def _add(a, b, alpha=1):
    return a + alpha * b


@register_aten("aten.sub.Tensor")
def _sub(a, b, alpha=1):
    return a - alpha * b


@register_aten("aten.mul.Tensor", "aten.mul_.Tensor")
def _mul(a, b):
    return a * b


@register_aten("aten.div.Tensor")
def _div(a, b):
    return a / b


@register_aten("aten.pow.Tensor_Scalar")
def _pow(a, b):
    return a ** b


@register_aten("aten.neg.default")
def _neg(x):
    return -x


@register_aten("aten.exp.default")
def _exp(x):
    return jnp.exp(x)


@register_aten("aten.log.default")
def _log(x):
    return jnp.log(x)


@register_aten("aten.sqrt.default")
def _sqrt(x):
    return jnp.sqrt(x)


@register_aten("aten.rsqrt.default")
def _rsqrt(x):
    return jax.lax.rsqrt(x)


@register_aten("aten.layer_norm.default")
def _layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5,
                cudnn_enable=False):
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mu = x.mean(axis=axes, keepdims=True)
    var = x.var(axis=axes, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@register_aten("aten.group_norm.default")
def _group_norm(x, groups, weight=None, bias=None, eps=1e-5,
                cudnn_enabled=True):
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape(n, groups, c // groups, *spatial)
    axes = tuple(range(2, xg.ndim))
    mu = xg.mean(axis=axes, keepdims=True)
    var = xg.var(axis=axes, keepdims=True)
    xg = (xg - mu) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(x.shape)
    shape = (1, c) + (1,) * len(spatial)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@register_aten("aten.softmax.int", "aten._softmax.default")
def _softmax(x, dim, half_to_float=False):
    return jax.nn.softmax(x, axis=dim)


@register_aten("aten.log_softmax.int")
def _log_softmax(x, dim, dtype=None):
    return jax.nn.log_softmax(x, axis=dim)


@register_aten("aten.embedding.default")
def _embedding(weight, indices, padding_idx=-1, scale_grad=False, sparse=False):
    return weight[indices]


@register_aten("aten.dropout.default")
def _dropout(x, p, train):
    if not train or p == 0.0:
        return x
    keep = jax.random.bernoulli(_next_rng(), 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


@register_aten("aten.native_dropout.default")
def _native_dropout(x, p, train):
    if not train or p == 0.0:
        return x, jnp.ones(x.shape, jnp.bool_)
    keep = jax.random.bernoulli(_next_rng(), 1.0 - p, x.shape)
    out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return out, keep


@register_aten("aten._native_batch_norm_legit_functional.default")
def _batch_norm_functional(x, w, b, running_mean, running_var, training,
                           momentum, eps):
    """Training batch norm with running-stat threading (torch semantics:
    normalize with biased batch var, update running stats with unbiased
    var, running = (1-momentum)*running + momentum*batch)."""
    axes = (0,) + tuple(range(2, x.ndim))
    n = 1
    for a in axes:
        n *= x.shape[a]
    mean = x.mean(axis=axes)
    var = x.var(axis=axes)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    invstd = jax.lax.rsqrt(var + eps)
    out = (x - mean.reshape(shape)) * invstd.reshape(shape)
    if w is not None:
        out = out * w.reshape(shape)
    if b is not None:
        out = out + b.reshape(shape)
    unbiased = var * (n / max(n - 1, 1))
    new_rm = (1 - momentum) * running_mean + momentum * mean
    new_rv = (1 - momentum) * running_var + momentum * unbiased
    return out, mean, invstd, new_rm, new_rv


@register_aten("aten._native_batch_norm_legit_no_training.default")
def _batch_norm_eval(x, w, b, running_mean, running_var, momentum, eps):
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    invstd = jax.lax.rsqrt(running_var + eps)
    out = (x - running_mean.reshape(shape)) * invstd.reshape(shape)
    if w is not None:
        out = out * w.reshape(shape)
    if b is not None:
        out = out + b.reshape(shape)
    return out, jnp.zeros_like(running_mean), jnp.zeros_like(running_var)


@register_aten("aten.batch_norm.default")
def _batch_norm(x, w, b, running_mean, running_var, training, momentum,
                eps, cudnn_enabled=False):
    if training:
        out, _, _, _, _ = _batch_norm_functional(
            x, w, b, running_mean, running_var, True, momentum, eps)
        return out  # running-stat mutation needs the functionalized export
    out, _, _ = _batch_norm_eval(x, w, b, running_mean, running_var,
                                 momentum, eps)
    return out


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _ntuple(v, rank):
    if isinstance(v, int):
        return (v,) * rank
    t = tuple(v)
    return t * rank if len(t) == 1 else t


def _conv_dims(rank):
    s = "DHW"[3 - rank:]
    return ("NC" + s, "OI" + s, "NC" + s)


def _conv_transpose_nd(x, w, bias, stride, padding, dilation, output_padding,
                       groups):
    """torch ConvTransposeNd (N=1,2,3) == fractionally-strided conv:
    lhs_dilation = stride, kernel spatially flipped with in/out channels
    swapped (torch weight layout is [Cin, Cout/g, k...])."""
    rank = w.ndim - 2
    if rank not in (1, 2, 3):
        raise UnsupportedAtenOp(
            f"transposed convolution with {rank}D kernels")
    cin = w.shape[0]
    ks = w.shape[2:]
    stride = _ntuple(stride, rank)
    padding = _ntuple(padding, rank)
    dilation = _ntuple(dilation, rank)
    output_padding = _ntuple(output_padding, rank)
    # [Cin, Cout/g, k...] -> [g, Cin/g, Cout/g, ...] -> [Cout, Cin/g, ...]
    wg = w.reshape((groups, cin // groups, w.shape[1]) + ks)
    wg = jnp.swapaxes(wg, 1, 2).reshape(
        (groups * w.shape[1], cin // groups) + ks)
    wg = jnp.flip(wg, axis=tuple(range(2, 2 + rank)))
    pads = []
    for k, p, d, op in zip(ks, padding, dilation, output_padding):
        eff = d * (k - 1)
        pads.append((eff - p, eff - p + op))
    out = jax.lax.conv_general_dilated(
        x, wg, (1,) * rank, pads,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=_conv_dims(rank),
        feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * rank)
    return out


@register_aten("aten.conv_transpose1d.default")
@register_aten("aten.conv_transpose2d.input")
@register_aten("aten.conv_transpose3d.input")
def _conv_transpose_input(x, w, bias=None, stride=1, padding=0,
                          output_padding=0, groups=1, dilation=1):
    return _conv_transpose_nd(x, w, bias, stride, padding, dilation,
                              output_padding, groups)


@register_aten("aten.conv1d.default", "aten.conv2d.default",
               "aten.conv3d.default", "aten.convolution.default")
def _conv_nd(x, w, bias=None, stride=1, padding=0, dilation=1, *rest):
    # torch NC<spatial> / OI<spatial>; groups is the last convolution arg
    # when present.  Rank (1/2/3D) comes from the kernel.
    rank = w.ndim - 2
    groups = 1
    transposed = False
    output_padding = 0
    if rest:
        if len(rest) >= 3:  # convolution.default: transposed, output_padding, groups
            transposed = bool(rest[0])
            output_padding = tuple(rest[1]) if rest[1] else 0
            groups = rest[2]
        else:
            groups = rest[0]
    stride = _ntuple(stride, rank)
    padding = _ntuple(padding, rank)
    dilation = _ntuple(dilation, rank)
    if transposed:
        return _conv_transpose_nd(x, w, bias, stride, padding, dilation,
                                  output_padding, groups)
    out = jax.lax.conv_general_dilated(
        x, w, stride,
        [(p, p) for p in padding],
        rhs_dilation=dilation,
        dimension_numbers=_conv_dims(rank),
        feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * rank)
    return out


def _ceil_extra(n, k, s, p, d):
    """Extra high-side padding so reduce_window covers torch's ceil_mode
    windows; torch ignores windows starting entirely in the padding."""
    eff = d * (k - 1) + 1
    out_ceil = -(-(n + 2 * p - eff) // s) + 1
    # last window must start inside the (left-padded) input
    if (out_ceil - 1) * s >= n + p:
        out_ceil -= 1
    return max((out_ceil - 1) * s + eff - (n + 2 * p), 0)


@register_aten("aten.max_pool2d.default")
def _max_pool2d(x, kernel, stride=None, padding=(0, 0), dilation=(1, 1),
                ceil_mode=False):
    kernel = _pair(kernel)
    stride = _pair(stride or kernel)
    padding, dilation = _pair(padding), _pair(dilation)
    pads = [(p, p) for p in padding]
    if ceil_mode:
        pads = [(p, p + _ceil_extra(n, k, s, p, d))
                for n, k, s, (p, _), d in zip(x.shape[2:], kernel, stride,
                                              pads, dilation)]
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, 1) + tuple(kernel), (1, 1) + tuple(stride),
        [(0, 0), (0, 0)] + pads,
        window_dilation=(1, 1) + tuple(dilation))


def _adaptive_weights(n, o, dtype):
    """[o, n] row-stochastic matrix averaging torch's adaptive windows
    (start = floor(i*n/o), end = ceil((i+1)*n/o)); static shapes, so the
    variable windows become one small matmul — MXU-friendly."""
    import numpy as np

    m = np.zeros((o, n), dtype=np.float32)
    for i in range(o):
        s, e = (i * n) // o, -((-(i + 1) * n) // o)
        m[i, s:e] = 1.0 / (e - s)
    return jnp.asarray(m, dtype=dtype)


def _adaptive_avg_pool_nd(x, output_size, rank):
    out = _ntuple(tuple(output_size) if not isinstance(output_size, int)
                  else output_size, rank)
    spatial = x.shape[-rank:]
    if all(o == 1 for o in out):
        return x.mean(axis=tuple(range(x.ndim - rank, x.ndim)),
                      keepdims=True)
    if all(n % o == 0 for n, o in zip(spatial, out)):
        # evenly-divisible: non-overlapping kernel = stride = n/o (torch
        # uses the same fixed windows here)
        ks = tuple(n // o for n, o in zip(spatial, out))
        lead = (1,) * (x.ndim - rank)
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, lead + ks, lead + ks,
            [(0, 0)] * x.ndim)
        import math
        return summed / math.prod(ks)
    # general case: contract each spatial dim with its window-weight matrix
    compute = x.astype(jnp.float32) if x.dtype != jnp.float64 else x
    for d, (n, o) in enumerate(zip(spatial, out)):
        axis = x.ndim - rank + d
        w = _adaptive_weights(n, o, compute.dtype)
        compute = jnp.moveaxis(
            jnp.tensordot(compute, w, axes=((axis,), (1,))), -1, axis)
    return compute.astype(x.dtype)


@register_aten("aten.adaptive_avg_pool1d.default")
def _adaptive_avg_pool1d(x, output_size):
    return _adaptive_avg_pool_nd(x, output_size, 1)


@register_aten("aten.adaptive_avg_pool2d.default")
def _adaptive_avg_pool2d(x, output_size):
    return _adaptive_avg_pool_nd(x, output_size, 2)


@register_aten("aten.adaptive_avg_pool3d.default")
def _adaptive_avg_pool3d(x, output_size):
    return _adaptive_avg_pool_nd(x, output_size, 3)


@register_aten("aten.mean.dim")
def _mean_dim(x, dims, keepdim=False, dtype=None):
    return x.mean(axis=tuple(dims), keepdims=keepdim)


@register_aten("aten.mean.default")
def _mean(x, dtype=None):
    return x.mean()


@register_aten("aten.sum.dim_IntList")
def _sum_dim(x, dims, keepdim=False, dtype=None):
    return x.sum(axis=tuple(dims), keepdims=keepdim)


@register_aten("aten.sum.default")
def _sum(x, dtype=None):
    return x.sum()


@register_aten("aten.prod.default")
def _prod(x, dtype=None):
    return x.prod()


@register_aten("aten.max.default")
def _max_full(x):
    return x.max()


@register_aten("aten.min.default")
def _min_full(x):
    return x.min()


@register_aten("aten.max.dim")
def _max_dim(x, dim, keepdim=False):
    return (x.max(axis=dim, keepdims=keepdim),
            x.argmax(axis=dim, keepdims=keepdim))


@register_aten("aten.min.dim")
def _min_dim(x, dim, keepdim=False):
    return (x.min(axis=dim, keepdims=keepdim),
            x.argmin(axis=dim, keepdims=keepdim))


@register_aten("aten.prod.dim_int")
def _prod_dim(x, dim, keepdim=False, dtype=None):
    return x.prod(axis=dim, keepdims=keepdim)


@register_aten("aten.var.correction")
def _var(x, dims=None, correction=1, keepdim=False):
    ddof = int(correction) if correction is not None else 1
    return x.var(axis=tuple(dims) if dims else None, ddof=ddof,
                 keepdims=keepdim)


@register_aten("aten.view.default", "aten.reshape.default",
               "aten._unsafe_view.default")
def _view(x, shape):
    return x.reshape(tuple(shape))


@register_aten("aten.permute.default")
def _permute(x, dims):
    return jnp.transpose(x, tuple(dims))


@register_aten("aten.transpose.int")
def _transpose(x, d0, d1):
    return jnp.swapaxes(x, d0, d1)


@register_aten("aten.t.default")
def _t(x):
    return x.T


@register_aten("aten.contiguous.default", "aten.clone.default",
               "aten.detach.default", "aten.alias.default",
               "aten.lift_fresh_copy.default",
               # export-time metadata assertion (emitted for .to() calls):
               # shapes/dtypes are static under jax tracing, so it holds
               # by construction
               "aten._assert_tensor_metadata.default")
def _identity(x, *a, **k):
    return x


@register_aten("aten.unsqueeze.default")
def _unsqueeze(x, dim):
    return jnp.expand_dims(x, dim)


@register_aten("aten.squeeze.dim")
def _squeeze(x, dim):
    return jnp.squeeze(x, axis=dim)


@register_aten("aten.cat.default")
def _cat(tensors, dim=0):
    # torch.cat accepts zero-element 1-D tensors whatever the target rank
    # (the legacy empty-tensor special case) — HF attention concatenates an
    # empty past_key_value placeholder with the fresh K/V this way
    tensors = [t for t in tensors
               if not (t.ndim == 1 and t.shape[0] == 0)] or tensors[:1]
    return jnp.concatenate(tensors, axis=dim)


@register_aten("aten.stack.default")
def _stack(tensors, dim=0):
    return jnp.stack(tensors, axis=dim)


@register_aten("aten.split.Tensor")
def _split(x, size, dim=0):
    n = x.shape[dim]
    sizes = [size] * (n // size) + ([n % size] if n % size else [])
    idx = np.cumsum(sizes)[:-1]
    return jnp.split(x, idx, axis=dim)


@register_aten("aten.chunk.default")
def _chunk(x, chunks, dim=0):
    # torch.chunk: chunk size = ceil(n/chunks), possibly FEWER chunks than
    # asked (chunk(6, 4) -> [2, 2, 2]); jnp.array_split would give
    # [2, 2, 1, 1] and break the traced getitem shapes.
    n = x.shape[dim]
    if n == 0:
        return [x] * chunks
    size = -(-n // chunks)
    return jnp.split(x, list(range(size, n, size)), axis=dim)


@register_aten("aten.slice.Tensor")
def _slice(x, dim=0, start=None, end=None, step=1):
    index = [slice(None)] * x.ndim
    index[dim] = slice(start, end if end not in (None, 2**63 - 1) else None,
                       step)
    return x[tuple(index)]


@register_aten("aten.select.int")
def _select(x, dim, index):
    return jnp.take(x, index, axis=dim)


@register_aten("aten.expand.default")
def _expand(x, sizes, implicit=False):
    # torch aligns sizes from the RIGHT; extra leading entries add new dims
    offset = len(sizes) - x.ndim
    shape = []
    for i, s in enumerate(sizes):
        src = i - offset
        if s == -1:
            if src < 0:
                raise UnsupportedAtenOp("expand: -1 in a new leading dim")
            shape.append(x.shape[src])
        else:
            shape.append(s)
    x = x.reshape((1,) * offset + x.shape) if offset > 0 else x
    return jnp.broadcast_to(x, tuple(shape))


@register_aten("aten.index.Tensor")
def _index_tensor(x, indices):
    """Advanced indexing x[idx0, idx1, ...]; None entries keep the dim."""
    for i in indices:
        if i is not None and getattr(i, "dtype", None) == jnp.bool_:
            raise UnsupportedAtenOp(
                "aten.index.Tensor with a boolean mask (data-dependent "
                "output shape); use jnp.where-style masking instead")
    idx = tuple(slice(None) if i is None else i for i in indices)
    return x[idx]


@register_aten("aten.index_select.default")
def _index_select(x, dim, index):
    return jnp.take(x, index, axis=dim)


@register_aten("aten.lt.Scalar", "aten.lt.Tensor")
def _lt(a, b):
    return a < b


@register_aten("aten.le.Scalar", "aten.le.Tensor")
def _le(a, b):
    return a <= b


@register_aten("aten.gt.Scalar", "aten.gt.Tensor")
def _gt(a, b):
    return a > b


@register_aten("aten.ge.Scalar", "aten.ge.Tensor")
def _ge(a, b):
    return a >= b


@register_aten("aten.eq.Scalar", "aten.eq.Tensor")
def _eq(a, b):
    return a == b


@register_aten("aten.ne.Scalar", "aten.ne.Tensor")
def _ne(a, b):
    return a != b


@register_aten("aten.masked_fill.Scalar")
def _masked_fill(x, mask, value):
    return jnp.where(mask, jnp.array(value, x.dtype), x)


@register_aten("aten.masked_fill.Tensor")
def _masked_fill_tensor(x, mask, value):
    return jnp.where(mask, value.astype(x.dtype), x)


@register_aten("aten.index_put.default", "aten.index_put_.default")
def _index_put(x, indices, values, accumulate=False):
    """x[idx...] = values.  Boolean-mask writes keep static shapes
    (x[mask] = v is a where/add — unlike boolean-mask READS, which have
    data-dependent output shapes and stay unsupported); integer indices go
    through scatter."""
    values = jnp.asarray(values).astype(x.dtype)
    masks = [i for i in indices if i is not None
             and getattr(i, "dtype", None) == jnp.bool_]
    if masks:
        if len(masks) != len([i for i in indices if i is not None]):
            raise UnsupportedAtenOp(
                "index_put mixing boolean masks with integer indices")
        if values.ndim > 0 and values.size > 1:
            # torch fills selected elements in row-major SELECTION order —
            # a data-dependent scatter; jnp.where would broadcast `values`
            # positionally over the full tensor and silently differ
            raise UnsupportedAtenOp(
                "index_put with a boolean mask and a non-scalar values "
                "tensor (selection-ordered fill is data-dependent)")
        # a mask at index position k covers dims k..k+mask.ndim-1 (torch
        # advanced-indexing semantics; `x[:, m]` exports as [None, m]) —
        # place each mask's dims at its position and AND them together
        mask = None
        pos = 0
        for i in indices:
            if i is None:
                pos += 1
                continue
            shape = [1] * pos + list(i.shape) \
                + [1] * (x.ndim - pos - i.ndim)
            m = i.reshape(shape)
            mask = m if mask is None else mask & m
            pos += i.ndim
        if accumulate:
            return x + jnp.where(mask, values, 0)
        return jnp.where(mask, values, x)
    idx = tuple(slice(None) if i is None else i for i in indices)
    if accumulate:
        return x.at[idx].add(values)
    return x.at[idx].set(values)


@register_aten("aten.where.self")
def _where(cond, a, b):
    return jnp.where(cond, a, b)


@register_aten("aten.triu.default")
def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register_aten("aten.tril.default")
def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_aten("aten.arange.default", "aten.arange.start")
def _arange(*args, dtype=None, layout=None, device=None, pin_memory=None):
    return jnp.arange(*args)


def _flash_eligible(q, k, v, attn_mask, dropout_p):
    """Kernel auto-substitution gate: the Pallas flash kernels handle
    4D [b, h, s, d] self-attention without an explicit mask (causal rides
    the kernel's block skipping), equal q/k seq, lane-friendly shapes."""
    if attn_mask is not None or dropout_p:
        return False
    if not (q.ndim == 4 and k.ndim == 4 and v.ndim == 4):
        return False
    s_q, d = q.shape[-2], q.shape[-1]
    if k.shape[-2] != s_q or v.shape[-2] != s_q:
        return False
    return s_q >= 256 and s_q % 128 == 0 and 8 <= d <= 256 and d % 8 == 0


@register_aten("aten.scaled_dot_product_attention.default")
def _sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None):
    if _flash_eligible(q, k, v, attn_mask, dropout_p):
        # torch.compile-style kernel substitution, TPU-native: route SDPA
        # to the Pallas flash-attention custom-vjp (fwd+bwd kernels) so
        # converted HF-style models train with fused attention.  Happens
        # pre-autodiff — jax differentiates through the custom_vjp.
        from easydist_tpu.ops import flash_attention

        return flash_attention(q, k, v, causal=bool(is_causal), scale=scale)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if is_causal:
        t_q, t_k = q.shape[-2], k.shape[-2]
        qi = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(ki <= qi, s, jnp.array(-1e30, s.dtype))
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            s = jnp.where(attn_mask, s, jnp.array(-1e30, s.dtype))
        else:
            s = s + attn_mask
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p:
        # torch sdpa training semantics: dropout on the attention weights
        # with 1/(1-p) rescale.  Rides the same per-site rng machinery as
        # aten.dropout (semantically equivalent to eager torch; the masks
        # themselves come from a different generator, like all dropout
        # here).  Silently skipping it trained without attention dropout.
        keep = jax.random.bernoulli(_next_rng(
            hint="scaled_dot_product_attention with dropout_p>0 in an "
                 "EVAL-mode export has no rng to draw from; re-export "
                 "with train=True, or pass dropout_p=0.0 when the module "
                 "is not training"), 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0).astype(p.dtype)
    return jnp.einsum("...qk,...kd->...qd", p, v)


@register_aten("aten.batch_norm.default")
def _batch_norm(x, weight, bias, running_mean, running_var, training,
                momentum, eps, cudnn_enabled=True):
    # inference semantics (running stats); training BN needs stat plumbing
    shape = (1, -1) + (1,) * (x.ndim - 2)
    out = (x - running_mean.reshape(shape)) * jax.lax.rsqrt(
        running_var.reshape(shape) + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


# --------------------------------------------------------------- converter

def _to_jax_value(val):
    import torch

    if isinstance(val, torch.Tensor):
        # jnp.array COPIES (asarray of a torch-backed numpy view is
        # zero-copy on CPU: a later in-place torch mutation would race
        # jax's async execution and silently corrupt results)
        return jnp.array(val.detach().cpu().numpy())
    return val


def torch_module_to_jax(module, example_args, train: bool = False):
    """Export a torch nn.Module and convert to (jax_fn, params).

    Returns (fn, params) where params is a {qualified_name: jax array} dict
    of parameters AND buffers.

    train=False: fn(params, *inputs) reproduces the eval-mode torch forward
    (single tensor or tuple output, matching torch).

    train=True: the module is exported in training mode and functionalized
    (reference torch/compile.py:25-95 traces the training graph through
    fx; here torch.export.run_decompositions surfaces buffer mutations as
    outputs).  fn(params, rng, *inputs) -> (outputs, new_buffers) where
    `rng` drives dropout masks and `new_buffers` is a {qualified_name:
    value} dict of mutated buffers (batch-norm running stats) to merge back
    into params for the next step.
    """
    import torch

    if train:
        ep = torch.export.export(module.train(),
                                 tuple(example_args)).run_decompositions({})
    else:
        ep = torch.export.export(module.eval(), tuple(example_args))
    gm = ep.graph_module
    sig = ep.graph_signature
    mutated = {}  # output arg name -> buffer qualname
    if train:
        mutated = dict(getattr(sig, "buffers_to_mutate", {}) or {})
    state = {**ep.state_dict, **getattr(ep, "constants", {})}

    placeholder_specs: List = []  # ("state", qualname) | ("input", pos)
    user_pos = 0
    to_state = {}
    to_state.update(sig.inputs_to_parameters)
    to_state.update(sig.inputs_to_buffers)
    to_state.update(getattr(sig, "inputs_to_lifted_tensor_constants", {}) or {})
    for node in gm.graph.nodes:
        if node.op != "placeholder":
            continue
        if node.target in to_state:
            placeholder_specs.append(("state", to_state[node.target]))
        else:
            placeholder_specs.append(("input", user_pos))
            user_pos += 1

    params = {name: _to_jax_value(state[name])
              for spec, name in placeholder_specs if spec == "state"
              for name in [name]}

    node_list = list(gm.graph.nodes)

    def run_graph(params, inputs, rng=None):
        env: Dict[Any, Any] = {}
        ph_iter = iter(placeholder_specs)
        if rng is not None:
            _set_rng(rng)

        def lookup(arg):
            if isinstance(arg, (list, tuple)):
                return type(arg)(lookup(a) for a in arg)
            if hasattr(arg, "op"):  # fx.Node
                return env[arg]
            return arg

        for node in node_list:
            if node.op == "placeholder":
                kind, key = next(ph_iter)
                env[node] = params[key] if kind == "state" else inputs[key]
            elif node.op == "call_function":
                import operator

                if node.target is operator.getitem:
                    obj, idx = node.args
                    env[node] = lookup(obj)[idx]
                    continue
                name = str(node.target)
                impl = _ATEN.get(name)
                if impl is None:
                    raise UnsupportedAtenOp(
                        f"no jax mapping for {name}; register one with "
                        f"easydist_tpu.torchfront.convert.register_aten")
                args = lookup(node.args)
                kwargs = {k: lookup(v) for k, v in node.kwargs.items()}
                env[node] = impl(*args, **kwargs)
            elif node.op == "get_attr":
                env[node] = _to_jax_value(getattr(gm, node.target))
            elif node.op == "output":
                _set_rng(None)
                raw = node.args[0]
                if mutated:
                    # leading outputs are functionalized buffer mutations
                    new_buffers = {}
                    user_out = []
                    for arg in raw:
                        name = getattr(arg, "name", None)
                        if name in mutated:
                            new_buffers[mutated[name]] = lookup(arg)
                        else:
                            user_out.append(lookup(arg))
                    out = user_out[0] if len(user_out) == 1 \
                        else tuple(user_out)
                    return out, new_buffers
                out = lookup(raw)
                out = out[0] if isinstance(out, (list, tuple)) \
                    and len(out) == 1 else out
                return (out, {}) if train else out
        raise RuntimeError("graph had no output node")

    if train:
        def fn(params, rng, *inputs):
            return run_graph(params, inputs, rng=rng)
    else:
        def fn(params, *inputs):
            return run_graph(params, inputs)

    # which param-dict entries are buffers (running stats etc.) — training
    # steps must exclude them from autodiff and thread their updates
    fn.buffer_names = frozenset(
        (sig.inputs_to_buffers or {}).values()) | frozenset(
        (getattr(sig, "inputs_to_lifted_tensor_constants", {}) or {}).values())
    # the aten surface of the exported graph, for capability checks (e.g.
    # the torch pp path rejects active dropout)
    fn.aten_ops = frozenset(str(n.target) for n in node_list
                            if n.op == "call_function")

    # ops that would draw randomness at runtime (dropout with p>0,
    # sdpa with dropout_p>0) — the pp path must reject these, and a
    # name-substring check misses sdpa's argument-carried dropout
    fn.stochastic_ops = frozenset(
        str(n.target) for n in node_list
        if n.op == "call_function" and _node_is_stochastic(n))
    # buffers the module MUTATES (batch-norm running stats) vs constant
    # buffers (causal masks etc) — only the former block pipelining
    fn.mutated_buffer_names = frozenset(mutated.values()) if train \
        else frozenset()
    return fn, params


def _node_is_stochastic(n):
    """Would this exported-graph node draw randomness at runtime?

    dropout(x, p, train) and sdpa(..., dropout_p=...) may carry p/train in
    EITHER positional args or kwargs depending on how the export
    normalized the call — reading only positionals would misclassify a
    kwargs-carrying dropout as deterministic and let the pp path silently
    train with a frozen step-invariant rng (ADVICE r5 #4)."""
    t = str(n.target)
    if "dropout" in t:
        pval = n.kwargs.get("p", n.args[1] if len(n.args) > 1 else 0.0)
        # dropout(x, p, train): train=False is eval-frozen — fully
        # deterministic regardless of p (r5 review #1)
        train_flag = n.kwargs.get(
            "train", n.args[2] if len(n.args) > 2 else None)
        if train_flag is False:
            return False
    elif "scaled_dot_product_attention" in t:
        # (q, k, v, attn_mask=None, dropout_p=0.0, ...)
        pval = n.kwargs.get(
            "dropout_p", n.args[4] if len(n.args) > 4 else 0.0)
    else:
        return False
    # a non-literal p (traced tensor) is conservatively stochastic
    return not isinstance(pval, (int, float)) or pval > 0.0


@register_aten("aten.flatten.using_ints")
def _flatten(x, start_dim=0, end_dim=-1):
    end_dim = end_dim if end_dim >= 0 else x.ndim + end_dim
    shape = x.shape[:start_dim] + (-1,) + x.shape[end_dim + 1:]
    return x.reshape(shape)


@register_aten("aten.unbind.int")
def _unbind(x, dim=0):
    return tuple(jnp.take(x, i, axis=dim) for i in range(x.shape[dim]))


@register_aten("aten.rsub.Scalar")
def _rsub(a, b, alpha=1):
    return b - alpha * a


@register_aten("aten.clamp.default")
def _clamp(x, min=None, max=None):
    return jnp.clip(x, min, max)


@register_aten("aten.pow.Tensor_Tensor")
def _pow_tt(a, b):
    return a ** b


@register_aten("aten.div.Scalar")
def _div_scalar(a, b):
    return a / b


@register_aten("aten.add.Scalar")
def _add_scalar(a, b, alpha=1):
    return a + alpha * b


@register_aten("aten.mul.Scalar")
def _mul_scalar(a, b):
    return a * b


@register_aten("aten.erf.default")
def _erf(x):
    return jax.scipy.special.erf(x)


@register_aten("aten.hardtanh.default")
def _hardtanh(x, min_val=-1.0, max_val=1.0):
    return jnp.clip(x, min_val, max_val)


@register_aten("aten.leaky_relu.default")
def _leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@register_aten("aten.elu.default")
def _elu(x, alpha=1.0, scale=1.0, input_scale=1.0):
    # torch: scale * (x if x > 0 else alpha * expm1(input_scale * x))
    return jnp.where(x > 0, scale * x,
                     scale * alpha * jnp.expm1(input_scale * x))


@register_aten("aten.avg_pool2d.default")
def _avg_pool2d(x, kernel, stride=None, padding=(0, 0), ceil_mode=False,
                count_include_pad=True, divisor_override=None):
    if divisor_override is not None:
        raise UnsupportedAtenOp("avg_pool2d with divisor_override")
    kernel = _pair(kernel)
    stride = _pair(stride or kernel)
    padding = _pair(padding)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    extra = [(_ceil_extra(n, k, s, p, 1) if ceil_mode else 0)
             for n, k, s, p in zip(x.shape[2:], kernel, stride, padding)]
    pads = [(0, 0), (0, 0)] + [(p, p + e) for p, e in zip(padding, extra)]
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                   pads)
    if count_include_pad:
        if not ceil_mode:
            return summed / (kernel[0] * kernel[1])
        # explicit padding counts toward the divisor; the implicit ceil
        # extension never does (torch semantics): count ones over the
        # explicitly-padded input with only the ceil extension as zero-pad
        xp_ones = jnp.ones(
            x.shape[:2] + tuple(n + 2 * p for n, p in
                                zip(x.shape[2:], padding)), x.dtype)
        counts = jax.lax.reduce_window(
            xp_ones, 0.0, jax.lax.add, window, strides,
            [(0, 0), (0, 0)] + [(0, e) for e in extra])
        return summed / counts
    counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                   window, strides, pads)
    return summed / counts


@register_aten("aten.amax.default")
def _amax(x, dims=None, keepdim=False):
    return x.max(axis=tuple(dims) if dims else None, keepdims=keepdim)


@register_aten("aten.amin.default")
def _amin(x, dims=None, keepdim=False):
    return x.min(axis=tuple(dims) if dims else None, keepdims=keepdim)


@register_aten("aten.minimum.default")
def _minimum(a, b):
    return jnp.minimum(a, b)


@register_aten("aten.maximum.default")
def _maximum(a, b):
    return jnp.maximum(a, b)


@register_aten("aten.abs.default")
def _abs(x):
    return jnp.abs(x)


@register_aten("aten.cumsum.default")
def _cumsum(x, dim, dtype=None):
    return jnp.cumsum(x, axis=dim)


@register_aten("aten.flip.default")
def _flip(x, dims):
    return jnp.flip(x, axis=tuple(dims))


@register_aten("aten.repeat.default")
def _repeat(x, repeats):
    offset = len(repeats) - x.ndim
    if offset > 0:
        x = x.reshape((1,) * offset + x.shape)
    return jnp.tile(x, tuple(repeats))


def _torch_dtype_to_jnp(dtype):
    if dtype is None:
        return None
    name = str(dtype).replace("torch.", "")
    return {"float32": jnp.float32, "float64": jnp.float64,
            "float16": jnp.float16, "bfloat16": jnp.bfloat16,
            "int64": jnp.int64, "int32": jnp.int32, "int16": jnp.int16,
            "int8": jnp.int8, "uint8": jnp.uint8, "bool": jnp.bool_}.get(
                name, jnp.float32)


@register_aten("aten.full.default")
def _full(size, fill_value, dtype=None, layout=None, device=None,
          pin_memory=None):
    return jnp.full(tuple(size), fill_value, dtype=_torch_dtype_to_jnp(dtype))


@register_aten("aten.zeros.default")
def _zeros(size, dtype=None, layout=None, device=None, pin_memory=None):
    return jnp.zeros(tuple(size), dtype=_torch_dtype_to_jnp(dtype))


@register_aten("aten.ones.default")
def _ones(size, dtype=None, layout=None, device=None, pin_memory=None):
    return jnp.ones(tuple(size), dtype=_torch_dtype_to_jnp(dtype))
