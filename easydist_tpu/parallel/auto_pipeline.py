"""Automatic pipeline splitting of arbitrary traced functions.

The reference pipelines arbitrary models by splitting the traced graph at
annotated or auto-balanced points (pp/compile_pipeline.py:60-230, 762-1087)
and shipping boundary tensors over NCCL P2P.  The TPU redesign keeps the
whole pipeline one SPMD program:

  1. trace `fn(params, x)` to a jaxpr (nested pjit calls inlined)
  2. split equations into n contiguous stages balanced by estimated FLOPs
  3. every value crossing a stage boundary (including residuals that skip
     stages — reference tests/test_torch/test_pp/test_reslink.py) travels in
     ONE padded f32 transport vector rotated with `lax.ppermute`; each
     stage's branch unpacks what it needs, computes its equation slice, and
     re-packs live values
  4. `lax.switch(stage_id, branches)` runs each device's own stage; jax
     autodiff through the scan yields the backward pipeline

Limitations (v1, documented): params are replicated across pp devices (use
`spmd_pipeline` with stage-stacked params for param-sharded PP) and
boundary-crossing values must be float (cast to f32 in transport).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.extend import core as jex_core
from jax.sharding import PartitionSpec as P

from easydist_tpu.jaxfront.inline import inline_calls

_HEAVY = {"dot_general", "conv_general_dilated"}


def _eqn_flops(eqn) -> float:
    if eqn.primitive.name not in _HEAVY:
        return 1.0
    out = sum(math.prod(v.aval.shape) for v in eqn.outvars)
    inp = max((math.prod(v.aval.shape) for v in eqn.invars
               if not isinstance(v, jex_core.Literal)), default=1)
    return float(out) * max(inp / max(out, 1), 1.0) * 2.0


def _balanced_splits(flops: Sequence[float], n: int) -> List[int]:
    """Contiguous split into n non-empty groups at cumulative-FLOP quantiles;
    returns strictly increasing end indices."""
    import numpy as np

    if n > len(flops):
        raise ValueError(f"n_stages={n} exceeds the {len(flops)} traced "
                         f"equations")
    cum = np.cumsum(np.asarray(flops, dtype=np.float64))
    total = float(cum[-1])
    ends: List[int] = []
    prev = 0
    for k in range(1, n):
        i = int(np.searchsorted(cum, total * k / n)) + 1
        i = max(i, prev + 1)  # every stage keeps >= 1 equation
        i = min(i, len(flops) - (n - k))
        ends.append(i)
        prev = i
    ends.append(len(flops))
    return ends


class _StagePlan:
    def __init__(self, closed_jaxpr, n_stages: int):
        jaxpr = closed_jaxpr.jaxpr
        self.closed = closed_jaxpr
        eqns = jaxpr.eqns
        ends = _balanced_splits([_eqn_flops(e) for e in eqns], n_stages)
        starts = [0] + ends[:-1]
        self.stage_eqns = [eqns[s:e] for s, e in zip(starts, ends)]
        self.n_stages = n_stages

        def_stage: Dict = {}
        for var in jaxpr.invars:
            def_stage[var] = -1  # globally available (replicated params/data)
        for var in jaxpr.constvars:
            def_stage[var] = -1
        for s, st_eqns in enumerate(self.stage_eqns):
            for e in st_eqns:
                for v in e.outvars:
                    def_stage[v] = s
        self.def_stage = def_stage

        last_use: Dict = {}
        for s, st_eqns in enumerate(self.stage_eqns):
            for e in st_eqns:
                for v in e.invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    last_use[v] = max(last_use.get(v, -1), s)
        for v in jaxpr.outvars:
            if not isinstance(v, jex_core.Literal):
                last_use[v] = self.n_stages - 1

        # boundary b carries vars defined at stage <= b, used at stage > b
        self.boundaries: List[List] = []
        for b in range(n_stages - 1):
            live = [v for v, d in def_stage.items()
                    if 0 <= d <= b and last_use.get(v, -1) > b]
            for v in live:
                if not jnp.issubdtype(v.aval.dtype, jnp.floating):
                    raise NotImplementedError(
                        f"non-float value {v.aval} crosses a pipeline "
                        f"boundary; place the split elsewhere")
            self.boundaries.append(live)

        self.out_vars = [v for v in jaxpr.outvars]
        for v in self.out_vars:
            aval = getattr(v, "aval", None)
            if aval is not None and not jnp.issubdtype(aval.dtype,
                                                      jnp.floating):
                raise NotImplementedError(
                    f"non-float output {aval} cannot ride the f32 output "
                    f"transport (would lose precision)")
        self.buf_elems = max(
            [sum(math.prod(v.aval.shape) for v in b)
             for b in self.boundaries] + [1])
        self.out_elems = max(sum(
            math.prod(getattr(v, "aval", v).shape) if hasattr(v, "aval")
            else 1 for v in self.out_vars), 1)

    def pack(self, values: List, total: int):
        parts = [jnp.ravel(v).astype(jnp.float32) for v in values]
        flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), jnp.float32)
        return jnp.pad(flat, (0, total - flat.shape[0]))

    def unpack(self, buf, variables: List):
        out, off = {}, 0
        for v in variables:
            n = math.prod(v.aval.shape)
            out[v] = buf[off:off + n].reshape(v.aval.shape).astype(v.aval.dtype)
            off += n
        return out


def pipeline_forward(fn: Callable, example_params, example_mb, mesh,
                     n_stages: int, n_microbatches: int, axis: str = "pp"):
    """Auto-split `fn(params, mb)` into a pipelined callable.

    Returns pipe(params, microbatches[M, ...mb shape]) -> stacked outputs
    [M, ...out shape] (replicated over pp).
    """
    closed = inline_calls(jax.make_jaxpr(fn)(example_params, example_mb))
    plan = _StagePlan(closed, n_stages)
    jaxpr = closed.jaxpr

    n_param_leaves = len(jax.tree_util.tree_leaves(example_params))
    param_vars = jaxpr.invars[:n_param_leaves]
    data_vars = jaxpr.invars[n_param_leaves:]
    S, M = n_stages, n_microbatches

    def make_branch(s: int):
        def branch(buf_in, param_vals, data_vals):
            env = {}
            for var, val in zip(param_vars, param_vals):
                env[var] = val
            for var, val in zip(data_vars, data_vals):
                env[var] = val
            for var, val in zip(jaxpr.constvars, closed.consts):
                env[var] = val
            if s > 0:
                env.update(plan.unpack(buf_in, plan.boundaries[s - 1]))

            def read(v):
                return v.val if isinstance(v, jex_core.Literal) else env[v]

            for eqn in plan.stage_eqns[s]:
                subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
                out = eqn.primitive.bind(*subfuns,
                                         *[read(v) for v in eqn.invars],
                                         **bind_params)
                if not eqn.primitive.multiple_results:
                    out = [out]
                for var, val in zip(eqn.outvars, out):
                    env[var] = val

            if s < S - 1:
                buf_out = plan.pack([env[v] for v in plan.boundaries[s]],
                                    plan.buf_elems)
                out_pack = jnp.zeros((plan.out_elems,), jnp.float32)
            else:
                buf_out = jnp.zeros((plan.buf_elems,), jnp.float32)
                out_pack = plan.pack([read(v) for v in plan.out_vars],
                                     plan.out_elems)
            return buf_out, out_pack

        return branch

    branches = [make_branch(s) for s in range(S)]

    def pipelined(params, microbatches):
        param_leaves = jax.tree_util.tree_leaves(params)
        mb_leaves = jax.tree_util.tree_leaves(microbatches)
        if len(mb_leaves) != len(data_vars):
            raise ValueError(
                f"microbatches pytree has {len(mb_leaves)} leaves; the traced "
                f"function expects {len(data_vars)}")

        @lambda f: shard_map(
            f, mesh=mesh,
            in_specs=(P(), tuple(P() for _ in mb_leaves)),
            out_specs=P(), check_vma=False)
        def run(param_vals, x_mb_leaves):
            stage_id = jax.lax.axis_index(axis)
            T = M + S - 1

            def tick(carry, t):
                buf, outputs = carry
                # stage s consumes microbatch t - s
                mb_idx = jnp.clip(t - stage_id, 0, M - 1)
                data_vals = [x[mb_idx] for x in x_mb_leaves]
                buf_out, out_pack = jax.lax.switch(
                    stage_id, branches, buf, list(param_vals), data_vals)
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                emit = jnp.logical_and(stage_id == S - 1, t >= S - 1)
                outputs = outputs.at[out_idx].set(
                    jnp.where(emit, out_pack, outputs[out_idx]))
                nxt = jax.lax.ppermute(
                    buf_out, axis, [(i, (i + 1) % S) for i in range(S)])
                return (nxt, outputs), None

            buf0 = jnp.zeros((plan.buf_elems,), jnp.float32)
            outs0 = jnp.zeros((M, plan.out_elems), jnp.float32)
            (_, outputs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(T))
            outputs = jax.lax.psum(
                jnp.where(stage_id == S - 1, outputs, jnp.zeros_like(outputs)),
                axis)
            return outputs

        packed = run(tuple(param_leaves), tuple(mb_leaves))  # [M, out_elems]
        # unpack each microbatch row back to the fn's output structure
        results = []
        off = 0
        shapes = [(tuple(v.aval.shape), v.aval.dtype) for v in plan.out_vars]
        for shape, dtype in shapes:
            n = math.prod(shape)
            results.append(packed[:, off:off + n]
                           .reshape((M,) + shape).astype(dtype))
            off += n
        return results[0] if len(results) == 1 else tuple(results)

    return pipelined
