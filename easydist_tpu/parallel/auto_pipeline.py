"""Automatic pipeline splitting of arbitrary traced functions.

The reference pipelines arbitrary models by splitting the traced graph at
annotated or auto-balanced points (pp/compile_pipeline.py:60-230, 762-1087)
and shipping boundary tensors over NCCL P2P.  The TPU redesign keeps the
whole pipeline one SPMD program:

  1. trace `fn(params, x)` to a jaxpr (nested pjit calls inlined)
  2. split equations into n contiguous stages balanced by estimated FLOPs
  3. every value crossing a stage boundary (including residuals that skip
     stages — reference tests/test_torch/test_pp/test_reslink.py) travels in
     ONE padded f32 transport vector rotated with `lax.ppermute`; each
     stage's branch unpacks what it needs, computes its equation slice, and
     re-packs live values
  4. `lax.switch(stage_id, branches)` runs each device's own stage; jax
     autodiff through the scan yields the backward pipeline

With `shard_params=True` stage-exclusive params live only on their stage's
pp group (packed rows sharded over `pp`); with `manual_siblings=True` the
whole pipeline runs as ONE fully-manual shard_map over every mesh axis and
the sibling (non-pp) axes data-parallelise each stage: the function must be
traced at sibling-local microbatch shape, packed param rows are additionally
flat-sharded over the siblings (ZeRO-style, gathered once per step at a
uniform program point) and the loss is sibling-averaged after the pipeline
scan.  Nothing inside the divergent `lax.switch` stage branches ever
communicates — the partial-auto design this replaces let GSPMD insert
resharding collective-permutes inside branches, which deadlocks (different
pp groups wait at different collectives; judge probe, VERDICT r4 weak #1).

Boundary-crossing values must be float (they ride a packed transport vector;
the wire narrows to bf16/f16 when every boundary value shares that dtype).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
from easydist_tpu.utils.jax_compat import shard_map
from jax.extend import core as jex_core
from jax.sharding import PartitionSpec as P

from easydist_tpu.jaxfront.inline import inline_calls

_HEAVY = {"dot_general", "conv_general_dilated"}


# ---------------------------------------------------------- split markers
# User-annotated split points (reference annotate_split_points,
# pp/compile_pipeline.py:60-78): `split_point(x)` is an identity that
# survives tracing as its own equation; _StagePlan cuts stages there.

split_point_p = jex_core.Primitive("ed_split_point")
split_point_p.def_impl(lambda x: x)
split_point_p.def_abstract_eval(lambda x: x)


def _register_split_rules():
    from jax.interpreters import ad, batching, mlir

    mlir.register_lowering(
        split_point_p, mlir.lower_fun(lambda x: x, multiple_results=False))
    ad.deflinear2(split_point_p, lambda ct, x: [ct])
    batching.primitive_batchers[split_point_p] = \
        lambda args, dims: (split_point_p.bind(args[0]), dims[0])


_register_split_rules()


def split_point(x):
    """Mark a pipeline split after this value: everything producing `x`
    belongs to the earlier stage.  N markers -> N+1 stages."""
    return split_point_p.bind(x)


def _eqn_flops(eqn) -> float:
    """Stage-balance weight: the bridge's estimator knows dot/conv
    dimension_numbers AND composite bodies (scan = length x body,
    cond = max branch, while = trips x body) — the old dot-only local
    heuristic weighted a whole scan-over-layers at 1.0 and packed all
    real compute into one stage."""
    from easydist_tpu.jaxfront.bridge import _eqn_flops as _bridge_flops

    return max(float(_bridge_flops(eqn)), 1.0)


def _balanced_splits(flops: Sequence[float], n: int) -> List[int]:
    """Contiguous split into n non-empty groups at cumulative-FLOP quantiles;
    returns strictly increasing end indices."""
    import numpy as np

    if n > len(flops):
        raise ValueError(f"n_stages={n} exceeds the {len(flops)} traced "
                         f"equations")
    cum = np.cumsum(np.asarray(flops, dtype=np.float64))
    total = float(cum[-1])
    ends: List[int] = []
    prev = 0
    for k in range(1, n):
        i = int(np.searchsorted(cum, total * k / n)) + 1
        i = max(i, prev + 1)  # every stage keeps >= 1 equation
        i = min(i, len(flops) - (n - k))
        ends.append(i)
        prev = i
    ends.append(len(flops))
    return ends


class _StagePlan:
    def __init__(self, closed_jaxpr, n_stages: int,
                 n_param_leaves: int = 0):
        jaxpr = closed_jaxpr.jaxpr
        self.closed = closed_jaxpr
        eqns = jaxpr.eqns
        marker_idx = [i for i, e in enumerate(eqns)
                      if e.primitive is split_point_p]
        if marker_idx:
            if len(marker_idx) != n_stages - 1:
                raise ValueError(
                    f"{len(marker_idx)} split_point markers imply "
                    f"{len(marker_idx) + 1} stages, but n_stages="
                    f"{n_stages}")
            ends = [i + 1 for i in marker_idx] + [len(eqns)]
        else:
            ends = _balanced_splits([_eqn_flops(e) for e in eqns], n_stages)
        starts = [0] + ends[:-1]
        self.stage_eqns = [eqns[s:e] for s, e in zip(starts, ends)]
        self.stage_starts = starts  # global eqn index of each stage's first
        self.n_stages = n_stages

        def_stage: Dict = {}
        for var in jaxpr.invars:
            def_stage[var] = -1  # globally available (replicated params/data)
        for var in jaxpr.constvars:
            def_stage[var] = -1
        for s, st_eqns in enumerate(self.stage_eqns):
            for e in st_eqns:
                for v in e.outvars:
                    def_stage[v] = s
        self.def_stage = def_stage

        last_use: Dict = {}
        for s, st_eqns in enumerate(self.stage_eqns):
            for e in st_eqns:
                for v in e.invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    last_use[v] = max(last_use.get(v, -1), s)
        for v in jaxpr.outvars:
            if not isinstance(v, jex_core.Literal):
                last_use[v] = self.n_stages - 1

        # non-float values cannot ride the float transport; when such a
        # value derives from invars/consts through a SHORT chain (causal
        # masks, index tables), consuming stages recompute it locally
        # instead of shipping it.  self.remat_chains: var -> topo-ordered
        # eqns rebuilding it from stage-locally-available inputs.
        producer_of = {}
        for e in eqns:
            for v in e.outvars:
                producer_of[v] = e
        # roots a stage branch is guaranteed to hold: DATA inputs (passed
        # to every branch) and consts — NOT params, which may be packed
        # onto a different stage (r5 review #3)
        always_avail = set(jaxpr.invars[n_param_leaves:]) \
            | set(jaxpr.constvars)
        self.remat_chains: Dict = {}

        def const_chain(v, budget=32):
            """Topo eqn chain computing v from data/consts through CHEAP
            ops only, or None (rooted at a param, passes real compute, or
            too long) — consuming stages re-run the chain, so duplicating
            a matmul would defeat the FLOP balance (r5 review #4)."""
            chain, seen = [], set()

            def visit(u):
                if u in always_avail or isinstance(u, jex_core.Literal):
                    return True
                e = producer_of.get(u)
                if e is None:
                    return False  # param invar or unknown
                if id(e) in seen:
                    return True
                if len(chain) >= budget or e.primitive.name in _HEAVY:
                    return False
                if not all(visit(w) for w in e.invars
                           if not isinstance(w, jex_core.Literal)):
                    return False
                seen.add(id(e))
                chain.append(e)
                return True

            return chain if visit(v) else None

        # boundary b carries vars defined at stage <= b, used at stage > b
        self.boundaries: List[List] = []
        for b in range(n_stages - 1):
            live = []
            for v, d in def_stage.items():
                if not (0 <= d <= b and last_use.get(v, -1) > b):
                    continue
                if jnp.issubdtype(v.aval.dtype, jnp.floating):
                    live.append(v)
                    continue
                if v not in self.remat_chains:
                    chain = const_chain(v)
                    if chain is None:
                        raise NotImplementedError(
                            f"non-float value {v.aval} crosses a pipeline "
                            f"boundary and does not derive from "
                            f"params/data by a short chain; place the "
                            f"split elsewhere")
                    self.remat_chains[v] = chain
            self.boundaries.append(live)

        self.out_vars = [v for v in jaxpr.outvars]
        for v in self.out_vars:
            aval = getattr(v, "aval", None)
            if aval is not None and not jnp.issubdtype(aval.dtype,
                                                      jnp.floating):
                raise NotImplementedError(
                    f"non-float output {aval} cannot ride the f32 output "
                    f"transport (would lose precision)")
        # wire dtype: when every boundary value shares one half-precision
        # dtype, rotate the transport in that dtype (half the ICI bytes);
        # mixed or wider dtypes keep the lossless f32 wire.  bf16<->f16
        # cross-casting would silently drop mantissa/exponent bits.
        bdts = {v.aval.dtype for b in self.boundaries for v in b}
        if len(bdts) == 1 and next(iter(bdts)) in (jnp.bfloat16,
                                                   jnp.float16):
            self.wire_dtype = next(iter(bdts))
        else:
            self.wire_dtype = jnp.float32
        self.buf_elems = max(
            [sum(math.prod(v.aval.shape) for v in b)
             for b in self.boundaries] + [1])
        self.out_elems = max(sum(
            math.prod(getattr(v, "aval", v).shape) if hasattr(v, "aval")
            else 1 for v in self.out_vars), 1)

    def plan_params(self, param_vars):
        """Assign each param leaf to the single stage using it (packed into
        that stage's sharded buffer) or to the replicated shared set (used
        by several stages / non-float).  Returns (stage_layouts,
        shared_idx) over param positions."""
        use_stages: Dict = {v: set() for v in param_vars}
        for s, st_eqns in enumerate(self.stage_eqns):
            for e in st_eqns:
                for v in e.invars:
                    if not isinstance(v, jex_core.Literal) \
                            and v in use_stages:
                        use_stages[v].add(s)
        stage_layouts: List[List[int]] = [[] for _ in self.stage_eqns]
        shared_idx: List[int] = []
        for i, v in enumerate(param_vars):
            stages = use_stages[v]
            # the packed buffer rides in f32: only <=32-bit floats survive
            # the round-trip losslessly; f64 (and ints) stay replicated
            packable = v.aval.dtype in (jnp.float32, jnp.bfloat16,
                                        jnp.float16)
            if len(stages) == 1 and packable:
                stage_layouts[next(iter(stages))].append(i)
            else:
                shared_idx.append(i)
        return stage_layouts, shared_idx

    def pack(self, values: List, total: int, dtype=jnp.float32):
        parts = [jnp.ravel(v).astype(dtype) for v in values]
        flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), dtype)
        return jnp.pad(flat, (0, total - flat.shape[0]))

    def unpack(self, buf, variables: List):
        out, off = {}, 0
        for v in variables:
            n = math.prod(v.aval.shape)
            out[v] = buf[off:off + n].reshape(v.aval.shape).astype(v.aval.dtype)
            off += n
        return out



class _PipelinePrep:
    """Shared front half of the auto-split pipeline builders: traced plan,
    per-stage param packing layout, and the heterogeneous stage branches."""


def _tp_convert(val, cur, want, tp_axis: str, tp_size: int):
    """Move a branch-local value between tp placements with explicit
    manual collectives.  Legal inside the divergent stage switch because
    every participant group lies within one pp coordinate (all its members
    run the same branch) — unlike GSPMD-inserted collectives, whose groups
    span the mesh (the r4 deadlock)."""
    from easydist_tpu.metashard.metair import Placement

    cur = cur or Placement.replicate()
    if want is None or want.is_partial():
        want = Placement.replicate()
    if repr(cur) == repr(want):
        return val
    if cur.is_shard():  # S -> R (and S -> S' via R)
        val = jax.lax.all_gather(val, tp_axis, axis=cur.dim, tiled=True)
    if want.is_shard():
        size = val.shape[want.dim]
        if size % tp_size != 0:
            # the solver guarantees divisibility at traced shapes; reaching
            # this means a plan/trace mismatch — failing loudly here beats
            # binding a full-size operand where a 1/n slice was expected
            # (a distant shape error at best, silent garbage at worst)
            raise ValueError(
                f"tp plan wants dim {want.dim} of shape {val.shape} "
                f"sharded {tp_size}-way but it does not divide")
        shard = size // tp_size
        idx = jax.lax.axis_index(tp_axis)
        val = jax.lax.dynamic_slice_in_dim(val, idx * shard, shard,
                                           want.dim)
    return val


def _grad_scale(x, factor: float):
    """Identity forward, cotangent scaled by `factor` on the backward.

    Used on params consumed REPLICATED under a tp axis: every tp lane then
    computes the identical full gradient, and the shard_map-level psum
    over the siblings would multiply it by n_tp — scaling each lane's
    cotangent by 1/n_tp makes that psum a mean for these params while
    tp-SHARDED params keep the plain sum their complementary weight-shard
    contributions need (r5 review #1)."""
    @jax.custom_vjp
    def f(v):
        return v

    f.defvjp(lambda v: (v, None), lambda _, g: (g * factor,))
    return f(x)


def _prepare_pipeline(fn, example_params, example_mb, mesh, n_stages,
                      axis, shard_params, manual_siblings, remat_stages,
                      tp_plan=None, tp_axis=None, closed=None):
    if manual_siblings and not shard_params:
        raise ValueError("manual_siblings=True requires shard_params=True")
    if tp_plan and (tp_axis is None or not manual_siblings):
        raise ValueError("tp_plan needs tp_axis and manual_siblings=True")
    if closed is None:
        closed = inline_calls(jax.make_jaxpr(fn)(example_params,
                                                 example_mb))
    n_param_leaves = len(jax.tree_util.tree_leaves(example_params))
    plan = _StagePlan(closed, n_stages, n_param_leaves=n_param_leaves)
    jaxpr = closed.jaxpr
    S = n_stages

    prep = _PipelinePrep()
    prep.plan = plan
    param_vars = jaxpr.invars[:n_param_leaves]
    data_vars = jaxpr.invars[n_param_leaves:]
    prep.sib_axes = tuple(n for n in mesh.axis_names if n != axis) \
        if manual_siblings else ()
    # batch parallelism lives on the non-tp siblings; a tp axis replicates
    # the data and splits tensors inside stages per tp_plan
    prep.batch_axes = tuple(n for n in prep.sib_axes
                            if tp_plan is None or n != tp_axis)

    # gradient-reduction class per param under tp: params whose EVERY use
    # is tp-sharded contribute complementary weight-shard grads (sum over
    # tp is exact); any replicated use means the lanes compute identical
    # grads and the sibling psum must average instead.  Mixed-use params
    # are forced fully replicated for consistency.
    mean_params = set()
    if tp_plan is not None:
        # An EMPTY plan still needs the mean treatment: the tp lanes then
        # run fully replicated, so every param's identical lane gradients
        # must average, not sum.  Mixed-use params (one tp-sharded use,
        # one replicated) are forced fully replicated — feeding a forced-
        # replicated input to an eqn whose OTHER operands stay sharded
        # would bind mismatched shapes, so such plan entries are dropped
        # to a fixed point (r5 review #1).
        tp_plan = dict(tp_plan)
        param_set = set(param_vars)
        while True:
            sharded_use, repl_use = set(), set()
            for idx, eqn in enumerate(jaxpr.eqns):
                strat = tp_plan.get(idx)
                var_pos = 0
                for v in eqn.invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    want = None
                    if strat is not None \
                            and var_pos < len(strat.in_placements):
                        want = strat.in_placements[var_pos]
                    var_pos += 1
                    if v in param_set:
                        if want is not None and want.is_shard():
                            sharded_use.add(v)
                        else:
                            repl_use.add(v)
            mean_params = {v for v in param_vars
                           if v in repl_use or v not in sharded_use}
            drop = []
            for idx, eqn in enumerate(jaxpr.eqns):
                strat = tp_plan.get(idx)
                if strat is None:
                    continue
                var_pos = 0
                for v in eqn.invars:
                    if isinstance(v, jex_core.Literal):
                        continue
                    want = strat.in_placements[var_pos] \
                        if var_pos < len(strat.in_placements) else None
                    var_pos += 1
                    if v in mean_params and want is not None \
                            and want.is_shard():
                        drop.append(idx)
                        break
            if not drop:
                break
            for idx in drop:
                del tp_plan[idx]

    stage_layouts = shared_pos = stage_param_elems = None
    if shard_params:
        stage_layouts, shared_pos = plan.plan_params(param_vars)
        stage_param_elems = max(
            [sum(math.prod(param_vars[i].aval.shape) for i in lay)
             for lay in stage_layouts] + [1])
        if manual_siblings:
            # rows are flat-split over the sibling axes: pad to a multiple
            n_sib = math.prod(mesh.shape[n] for n in mesh.axis_names
                              if n != axis)
            stage_param_elems = -(-stage_param_elems // n_sib) * n_sib

    tp_size = mesh.shape[tp_axis] if tp_axis else 1

    def make_branch(s: int):
        def branch(buf_in, param_vals, data_vals):
            env = {}
            place = {}  # var -> tp Placement (absent/None = replicated)
            if shard_params:
                local_buf, shared_vals = param_vals
                env.update(plan.unpack(
                    local_buf, [param_vars[i] for i in stage_layouts[s]]))
                for pos, val in zip(shared_pos, shared_vals):
                    env[param_vars[pos]] = val
            else:
                for var, val in zip(param_vars, param_vals):
                    env[var] = val
            for var, val in zip(data_vars, data_vals):
                env[var] = val
            for var, val in zip(jaxpr.constvars, closed.consts):
                env[var] = val
            if s > 0:
                env.update(plan.unpack(buf_in, plan.boundaries[s - 1]))
                # rebuild constant-derived non-float values this stage
                # consumes (they don't ride the float transport)
                needed = [v for v in plan.remat_chains
                          if v not in env and any(
                              v in e2.invars
                              for e2 in plan.stage_eqns[s])]
                done = set()
                for v in needed:
                    for e2 in plan.remat_chains[v]:
                        if id(e2) in done or all(o in env
                                                 for o in e2.outvars):
                            continue
                        done.add(id(e2))
                        sub2, bp2 = e2.primitive.get_bind_params(e2.params)
                        iv2 = [w.val if isinstance(w, jex_core.Literal)
                               else env[w] for w in e2.invars]
                        o2 = e2.primitive.bind(*sub2, *iv2, **bp2)
                        if not e2.primitive.multiple_results:
                            o2 = [o2]
                        for var2, val2 in zip(e2.outvars, o2):
                            env[var2] = val2

            if tp_plan is not None and mean_params:
                inv_t = 1.0 / tp_size
                for v in list(env):
                    if v in mean_params:
                        env[v] = _grad_scale(env[v], inv_t)

            def read(v):
                return v.val if isinstance(v, jex_core.Literal) else env[v]

            def read_tp(v, want):
                """Value converted to the strategy's tp placement."""
                if isinstance(v, jex_core.Literal):
                    return v.val
                if want is not None and want.is_shard() \
                        and v in mean_params:
                    want = None  # mixed-use params stay fully replicated
                return _tp_convert(env[v], place.get(v), want, tp_axis,
                                   tp_size)

            for local_i, eqn in enumerate(plan.stage_eqns[s]):
                subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
                strat = tp_plan.get(plan.stage_starts[s] + local_i) \
                    if tp_plan else None
                if strat is None:
                    invals = [read_tp(v, None) if tp_plan else read(v)
                              for v in eqn.invars]
                    out_places = None
                else:
                    invals, var_pos = [], 0
                    for v in eqn.invars:
                        if isinstance(v, jex_core.Literal):
                            invals.append(v.val)
                            continue
                        want = strat.in_placements[var_pos] \
                            if var_pos < len(strat.in_placements) else None
                        invals.append(read_tp(v, want))
                        var_pos += 1
                    out_places = list(strat.out_placements)
                out = eqn.primitive.bind(*subfuns, *invals, **bind_params)
                if not eqn.primitive.multiple_results:
                    out = [out]
                for k, (var, val) in enumerate(zip(eqn.outvars, out)):
                    p = out_places[k] if out_places \
                        and k < len(out_places) else None
                    if p is not None and p.is_partial():
                        # partial CREATED here (contracted sharded dim):
                        # resolve with one psum over tp.  A solver P that
                        # merely PROPAGATED an upstream partial was already
                        # resolved at its creation, so the local value is
                        # full and must not be summed again.
                        created = not any(
                            q is not None and q.is_partial()
                            for q in (strat.in_placements if strat else ()))
                        if created:
                            val = jax.lax.psum(val, tp_axis)
                        p = None
                    env[var] = val
                    if p is not None and p.is_shard():
                        place[var] = p

            def read_full(v):
                """Boundary/output values always cross stages replicated
                over tp (the transport layout is traced at full-tp shape)."""
                if isinstance(v, jex_core.Literal):
                    return v.val
                if tp_plan:
                    return _tp_convert(env[v], place.get(v), None, tp_axis,
                                       tp_size)
                return env[v]

            if s < S - 1:
                buf_out = plan.pack(
                    [read_full(v) for v in plan.boundaries[s]],
                    plan.buf_elems, plan.wire_dtype)
                out_pack = jnp.zeros((plan.out_elems,), jnp.float32)
            else:
                buf_out = jnp.zeros((plan.buf_elems,), plan.wire_dtype)
                out_pack = plan.pack([read_full(v) for v in plan.out_vars],
                                     plan.out_elems)
            return buf_out, out_pack

        return branch

    branches = [make_branch(s) for s in range(S)]
    if remat_stages:
        branches = [jax.checkpoint(b) for b in branches]
    prep.branches = branches

    def pack_params(params):
        """params pytree -> (packed [n_stages, max_elems], shared leaves).
        Place the packed array with NamedSharding(mesh, P(axis, siblings))
        (or let the pipelined jit's constraint do it) so each device holds
        only its slice of its stage's parameters."""
        leaves = jax.tree_util.tree_leaves(params)
        if len(leaves) != n_param_leaves:
            raise ValueError("params pytree does not match the example")
        rows = [plan.pack([leaves[i] for i in lay], stage_param_elems)
                for lay in stage_layouts]
        return jnp.stack(rows), tuple(leaves[i] for i in shared_pos)

    def unpack_params(packed_params):
        """Inverse of pack_params: (packed [n_stages, max_elems], shared
        leaves) -> flat param leaves in the ORIGINAL tree order (the caller
        unflattens with its params treedef).  Every leaf is covered by
        construction — plan_params assigns each index to exactly one stage
        layout or to the shared set — and the f32 wire holds f32/bf16/f16
        exactly, so pack -> unpack -> pack is bitwise-stable (the
        export_state_dict contract in jaxfront/pp_compile.py)."""
        packed, shared_vals = packed_params
        leaves: list = [None] * n_param_leaves
        for s, lay in enumerate(stage_layouts):
            row = packed[s]
            off = 0
            for i in lay:
                aval = param_vars[i].aval
                n = math.prod(aval.shape)
                leaves[i] = row[off:off + n].reshape(aval.shape) \
                    .astype(aval.dtype)
                off += n
        for pos, val in zip(shared_pos, shared_vals):
            leaves[pos] = val
        return leaves

    pack_params.unpack_params = unpack_params
    prep.pack_params = pack_params if shard_params else None

    # shard_map front matter shared by the gpipe and 1f1b builders:
    # data rides [M, batch, ...] with batch split over the BATCH siblings
    # (a tp axis sees the full batch and splits tensors inside stages)
    prep.data_spec = P(None, prep.batch_axes) if prep.batch_axes else P()

    def param_specs(shared_vals):
        return (P(axis, prep.sib_axes or None),
                tuple(P() for _ in shared_vals))

    prep.param_specs = param_specs

    def check_mb_leaves(mb_leaves):
        if len(mb_leaves) != len(data_vars):
            raise ValueError(
                f"microbatches pytree has {len(mb_leaves)} leaves; the "
                f"traced function expects {len(data_vars)}")

    prep.check_mb_leaves = check_mb_leaves
    return prep


def pipeline_forward(fn: Callable, example_params, example_mb, mesh,
                     n_stages: int, n_microbatches: int, axis: str = "pp",
                     shard_params: bool = False,
                     manual_siblings: bool = False,
                     remat_stages: bool = False,
                     tp_plan=None, tp_axis: str = None, closed=None):
    """Auto-split `fn(params, mb)` into a pipelined callable.

    Stages split at user `split_point` markers when present, else at
    FLOP-balanced cuts.  Returns pipe(params, microbatches[M, ...mb shape])
    -> stacked outputs [M, ...out shape] (replicated over pp).

    shard_params=True additionally returns pack_params: params whose leaves
    are used by exactly one stage live ONLY on that stage's device (packed
    [n_stages, max_bytes] buffer sharded over `pp` — per-device param
    memory ~1/n_stages); leaves used across stages stay replicated.  Call
    as pipe(pack_params(params), microbatches); the reference equivalent is
    the per-stage submod params of compile_pipeline.py:762-1087.

    manual_siblings=True (requires shard_params=True) runs the pipeline
    fully manual over EVERY mesh axis; the non-pp axes batch-parallelise
    each stage.  Contract: `fn` must have been traced at sibling-LOCAL
    microbatch shape (batch dim divided by the product of sibling axis
    sizes) and must reduce its per-example losses with a MEAN, because the
    pipeline sibling-averages the outputs (lax.pmean) after the scan.
    Packed param rows arrive flat-sharded over the siblings and are
    all-gathered once per step before the pipeline scan — a uniform
    program point, so the divergent stage branches stay collective-free.
    remat_stages=True wraps each stage branch in jax.checkpoint (gpipe
    backward holds all microbatch residuals; remat trades recompute).
    """
    prep = _prepare_pipeline(fn, example_params, example_mb, mesh,
                             n_stages, axis, shard_params, manual_siblings,
                             remat_stages, tp_plan=tp_plan, tp_axis=tp_axis,
                             closed=closed)
    plan, branches, sib_axes = prep.plan, prep.branches, prep.sib_axes
    S, M = n_stages, n_microbatches

    # build-time schedule lint: the auto-split gpipe clock is the same
    # u = s + m table family the analyzer verifies for the stacked path
    from easydist_tpu import config as edconfig

    if edconfig.enable_analyze:
        from easydist_tpu.analyze import (check_schedule_tables,
                                          gpipe_schedule_tables)

        check_schedule_tables(gpipe_schedule_tables(S, M), S, 1, M,
                              fwd_only=True, node="auto_pipeline/gpipe")

    def pipelined(params, microbatches):
        if shard_params:
            packed, shared_vals = params  # from pack_params
            param_arg = (packed, tuple(shared_vals))
            param_spec = prep.param_specs(shared_vals)
        else:
            param_arg = tuple(jax.tree_util.tree_leaves(params))
            param_spec = P()
        mb_leaves = jax.tree_util.tree_leaves(microbatches)
        prep.check_mb_leaves(mb_leaves)
        data_spec = prep.data_spec

        @lambda f: shard_map(
            f, in_specs=(param_spec, tuple(data_spec for _ in mb_leaves)),
            out_specs=P(), mesh=mesh, check_vma=False)
        def run(param_vals, x_mb_leaves):
            if shard_params:
                packed_local, shared_vals_l = param_vals
                if sib_axes:
                    # ZeRO-style: rows stored flat-sharded over the
                    # siblings; gather the full stage row ONCE per step at
                    # this uniform point (all devices reach it — the
                    # backward is the matching reduce-scatter)
                    packed_local = jax.lax.all_gather(
                        packed_local, sib_axes, axis=1, tiled=True)
                param_vals = (packed_local[0], shared_vals_l)
            stage_id = jax.lax.axis_index(axis)
            T = M + S - 1

            def tick(carry, t):
                buf, outputs = carry
                # stage s consumes microbatch t - s
                mb_idx = jnp.clip(t - stage_id, 0, M - 1)
                data_vals = [x[mb_idx] for x in x_mb_leaves]
                branch_params = (param_vals if shard_params
                                 else list(param_vals))
                buf_out, out_pack = jax.lax.switch(
                    stage_id, branches, buf, branch_params, data_vals)
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                emit = jnp.logical_and(stage_id == S - 1, t >= S - 1)
                outputs = outputs.at[out_idx].set(
                    jnp.where(emit, out_pack, outputs[out_idx]))
                nxt = jax.lax.ppermute(
                    buf_out, axis, [(i, (i + 1) % S) for i in range(S)])
                return (nxt, outputs), None

            buf0 = jnp.zeros((plan.buf_elems,), plan.wire_dtype)
            outs0 = jnp.zeros((M, plan.out_elems), jnp.float32)
            (_, outputs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(T))
            outputs = jax.lax.psum(
                jnp.where(stage_id == S - 1, outputs, jnp.zeros_like(outputs)),
                axis)
            if prep.batch_axes:
                # batch lanes each pipelined their own batch shard; the
                # mean-loss contract makes the global value their average
                # (uniform point; backward = the 1/n-scaled psum of dp).
                # tp lanes already hold identical psum-resolved outputs —
                # averaging over tp would scale their complementary
                # weight-shard grads by 1/n_tp on the backward, so the tp
                # axis is deliberately NOT reduced here.
                outputs = jax.lax.pmean(outputs, prep.batch_axes)
            return outputs

        packed = run(param_arg, tuple(mb_leaves))  # [M, out_elems]
        # unpack each microbatch row back to the fn's output structure
        results = []
        off = 0
        shapes = [(tuple(v.aval.shape), v.aval.dtype) for v in plan.out_vars]
        for shape, dtype in shapes:
            n = math.prod(shape)
            results.append(packed[:, off:off + n]
                           .reshape((M,) + shape).astype(dtype))
            off += n
        return results[0] if len(results) == 1 else tuple(results)

    if not shard_params:
        return pipelined
    return pipelined, prep.pack_params


_IDENTITY_PROBE: List[bool] = []


def _switch_preserves_residual_identity() -> bool:
    """Does this jax forward a branch-invariant input THROUGH `lax.switch`
    as a vjp residual with tracer identity intact?  Modern jax does (cond
    partial-eval forwards invariant residuals); 0.4.x repackages them as
    fresh switch outputs, so identity-based dedup can never match there.
    Probed once with a toy two-branch switch under abstract evaluation."""
    if _IDENTITY_PROBE:
        return _IDENTITY_PROBE[0]

    cheap = {"reshape", "convert_element_type", "slice", "squeeze"}

    def br(b, w):
        return jnp.tanh(b @ w.reshape(4, 4)), jnp.sum(w)

    branches = [jax.checkpoint(
        br, policy=lambda prim, *_, **__: prim.name not in cheap)] * 2

    def probe(w, b):
        pl = jax.tree_util.tree_leaves(w)
        _, vjp0 = jax.vjp(
            lambda w_, b_: jax.lax.switch(0, branches, b_, w_), w, b)
        lv = jax.tree_util.tree_leaves(vjp0)
        _IDENTITY_PROBE.append(
            any(l is q for l in lv for q in pl))
        return b

    try:
        jax.eval_shape(probe, jax.ShapeDtypeStruct((16,), jnp.float32),
                       jax.ShapeDtypeStruct((2, 4), jnp.float32))
    except Exception:  # probe must never break compilation
        _IDENTITY_PROBE.append(False)
    return _IDENTITY_PROBE[0]


def pipeline_1f1b_grad(fn: Callable, example_params, example_mb, mesh,
                       n_stages: int, n_microbatches: int, axis: str = "pp",
                       tp_plan=None, tp_axis: str = None, closed=None):
    """DAPPLE 1F1B on AUTO-SPLIT heterogeneous stages (VERDICT r4 #5).

    The gpipe auto-split path differentiates through the forward pipeline
    scan, so every stage holds all M microbatches of vjp residuals.  This
    builder runs the supertick schedule of `parallel/pipeline.py::
    spmd_pipeline_grad` on `_StagePlan`'s lax.switch branches instead of a
    homogeneous stacked stage: every supertick each device runs one
    (masked) forward of ITS OWN stage and one (masked) backward, keeping at
    most min(2S-1, M) residual slots in a ring — the O(S) 1F1B working set
    (reference ScheduleDAPPLE on arbitrary split models,
    pp/runtime.py:658-700).

    Contract: scalar mean-reduction loss output; params packed/ZeRO-flat
    and sibling axes fully manual exactly as `pipeline_forward` with
    `shard_params=True, manual_siblings=True`.  Gradients of the packed
    rows come back reduce-scattered over the siblings (the manual
    transpose of the per-step row all-gather).

    Returns (pipe_grad, pack_params): pipe_grad((packed, shared), mbs) ->
    (loss, (d_packed, d_shared)) with grads shaped/sharded like storage.
    """
    from .pipeline import _1f1b_schedule_tables

    prep = _prepare_pipeline(fn, example_params, example_mb, mesh,
                             n_stages, axis, shard_params=True,
                             manual_siblings=True, remat_stages=False,
                             tp_plan=tp_plan, tp_axis=tp_axis,
                             closed=closed)
    plan, sib_axes = prep.plan, prep.sib_axes
    # Residual-memory policy: the vjp residuals of a raw branch include the
    # weight tensors UNPACKED from the packed row (slice+reshape+cast per
    # stage) — distinct tracers from pv, so the identity rebuild below
    # cannot dedup them and each ring slot would carry a full copy.
    # Marking the cheap repack ops non-saveable makes autodiff save their
    # SOURCE (the packed row, a pv leaf the identity rebuild shares) and
    # re-slice at backward time: O(S) ring slots stay activation-sized.
    _cheap = {"dynamic_slice", "slice", "reshape", "convert_element_type",
              "squeeze", "broadcast_in_dim", "transpose", "concatenate",
              "pad"}

    def _policy(prim, *_, **__):
        return prim.name not in _cheap

    branches = [jax.checkpoint(b, policy=_policy) for b in prep.branches]
    S, M = n_stages, n_microbatches
    if len(plan.out_vars) != 1 \
            or tuple(plan.out_vars[0].aval.shape) != ():
        raise NotImplementedError(
            "1f1b auto-split supports a single scalar (mean) loss output")
    batch_axes = prep.batch_axes
    n_batch = math.prod(mesh.shape[n] for n in batch_axes) \
        if batch_axes else 1

    tables = _1f1b_schedule_tables(S, 1, M)  # V=1: no virtual chunks here
    U, R = tables["n_superticks"], tables["ring"]
    tree = jax.tree_util

    def pipe_grad(params, microbatches):
        packed, shared_vals = params
        param_arg = (packed, tuple(shared_vals))
        param_spec = prep.param_specs(shared_vals)
        mb_leaves = tree.tree_leaves(microbatches)
        prep.check_mb_leaves(mb_leaves)
        data_spec = prep.data_spec

        @lambda f: shard_map(
            f, in_specs=(param_spec, tuple(data_spec for _ in mb_leaves)),
            out_specs=(P(), param_spec), mesh=mesh, check_vma=False)
        def run(param_vals, x_mb_leaves):
            packed_local, shared_l = param_vals
            if sib_axes:
                packed_full = jax.lax.all_gather(
                    packed_local, sib_axes, axis=1, tiled=True)
            else:
                packed_full = packed_local
            pv = (packed_full[0], shared_l)
            stage_id = jax.lax.axis_index(axis)

            MF, FOK = jnp.asarray(tables["m_f"]), jnp.asarray(tables["f_ok"])
            MB, BOK = jnp.asarray(tables["m_b"]), jnp.asarray(tables["b_ok"])

            def fwd(pv_, buf_in, data_vals):
                return jax.lax.switch(stage_id, branches, buf_in, pv_,
                                      data_vals)

            # probe the vjp residual structure once (dead code after trace);
            # residual leaves that ARE a param leaf (tracer identity) are
            # rebuilt from pv at backward time, not stored per ring slot
            buf0 = jnp.zeros((plan.buf_elems,), plan.wire_dtype)
            data0 = [x[0] for x in x_mb_leaves]
            probe_leaves = tree.tree_leaves(pv)
            _, vjp0 = jax.vjp(lambda pv_, b: fwd(pv_, b, data0), pv, buf0)
            leaves0, res_tree = tree.tree_flatten(vjp0)
            shared_idx = [
                next((j for j, q in enumerate(probe_leaves) if l is q), -1)
                for l in leaves0]
            # fast-loud dedup guard (ADVICE r5 #3): the whole O(S) residual
            # budget rests on the packed param row (probe_leaves[0]) being
            # identity-shared with a vjp residual leaf so rings never store
            # it.  A jax upgrade that changes residual tracer identity
            # would otherwise silently store a full packed-row copy PER
            # RING SLOT — a memory regression only the long_duration gate
            # would catch.  Two legitimate exemptions degrade to a warning
            # instead of blocking a correct (just memory-heavier) program:
            # TP-rewritten branches consume per-device SLICES of the row
            # (identity with the raw row cannot hold; their memory has its
            # own compiled-temp-bytes gate), and jax versions whose
            # `lax.switch` partial-eval repackages invariant residuals
            # (probed once) never preserved identity to begin with.
            if 0 not in shared_idx:
                if tp_plan is None and _switch_preserves_residual_identity():
                    raise AssertionError(
                        "pipeline_1f1b_grad residual dedup broke: the "
                        "packed param row is no longer identity-shared "
                        "with any vjp residual leaf (jax residual "
                        "structure changed?); each ring slot would "
                        "silently carry a full packed-row copy — fix the "
                        "identity rebuild or the checkpoint policy in "
                        "parallel/auto_pipeline.py before shipping")
                import logging

                logging.getLogger(__name__).warning(
                    "[1f1b] packed-row residual is not identity-shared "
                    "(%s); each of the %d ring slots stores a packed-row "
                    "copy", "tp rewrite" if tp_plan is not None
                    else "this jax's switch drops residual identity", R)
            store_idx = [i for i, si in enumerate(shared_idx) if si < 0]
            rings0 = [jnp.zeros((R,) + tuple(leaves0[i].shape),
                                leaves0[i].dtype) for i in store_idx]

            # the scalar loss rides out_pack[0]; mean over M microbatches
            cot_seed = jnp.zeros((plan.out_elems,), jnp.float32) \
                .at[0].set(1.0 / M)
            dacc0 = tree.tree_map(jnp.zeros_like, pv)

            def tick(carry, u):
                act_in, g_in, rings, dacc, lacc = carry

                # ---- forward half: this device's stage on microbatch m_f
                m_f, f_ok = MF[u, stage_id], FOK[u, stage_id]
                data_vals = [x[m_f] for x in x_mb_leaves]
                (buf_out, out_pack), vjp = jax.vjp(
                    lambda pv_, b: fwd(pv_, b, data_vals), pv, act_in)
                leaves = tree.tree_flatten(vjp)[0]
                slot_f = m_f % R
                rings = [
                    r.at[slot_f].set(jnp.where(f_ok, leaves[i], r[slot_f]))
                    for r, i in zip(rings, store_idx)]

                # ---- backward half: the last stage turns around in the
                # same supertick (its fwd produced this microbatch's loss)
                m_b, b_ok = MB[u, stage_id], BOK[u, stage_id]
                pred = (stage_id == S - 1) & f_ok
                lacc = lacc + jnp.where(pred, out_pack[0], 0.0)

                pl = tree.tree_leaves(pv)
                slot_b = m_b % R
                stored = iter(range(len(store_idx)))
                rebuilt = [
                    pl[shared_idx[i]] if shared_idx[i] >= 0
                    else rings[next(stored)][slot_b]
                    for i in range(len(leaves))]
                cot_buf = jnp.where(stage_id == S - 1,
                                    jnp.zeros_like(buf_out), g_in)
                cot_out = jnp.where(stage_id == S - 1, cot_seed,
                                    jnp.zeros_like(cot_seed))
                dpv, dbuf = tree.tree_unflatten(res_tree, rebuilt)(
                    (cot_buf, cot_out))
                dacc = tree.tree_map(
                    lambda a, d: a + jnp.where(b_ok, d, 0), dacc, dpv)

                # activations ride up the ring, gradients ride down
                act_next = jax.lax.ppermute(
                    buf_out, axis, [(i, (i + 1) % S) for i in range(S)])
                g_next = jax.lax.ppermute(
                    dbuf, axis, [(i, (i - 1) % S) for i in range(S)])
                return (act_next, g_next, rings, dacc, lacc), None

            g0 = jnp.zeros((plan.buf_elems,), plan.wire_dtype)
            carry0 = (buf0, g0, rings0, dacc0, jnp.float32(0.0))
            (_, _, _, dacc, lacc), _ = jax.lax.scan(tick, carry0,
                                                    jnp.arange(U))

            loss = jax.lax.psum(
                jnp.where(stage_id == S - 1, lacc, 0.0), axis) / M
            d_row, d_shared = dacc
            # shared leaves: every stage contributes -> sum over pp
            d_shared = tuple(jax.lax.psum(d, axis) for d in d_shared)
            if batch_axes:
                # global loss is the BATCH-lane mean (tp lanes hold
                # identical psum-resolved values, so reducing over them
                # would be a no-op forward but wrongly implies 1/n_tp on
                # the backward)
                loss = jax.lax.pmean(loss, batch_axes)
            if sib_axes:
                # grads: mean over batch lanes (1/n_batch), SUM over tp
                # lanes (complementary weight-shard contributions); the
                # packed rows were all-gathered -> reduce-scatter back to
                # each lane's stored slice
                d_row = jax.lax.psum_scatter(
                    d_row, sib_axes, scatter_dimension=0,
                    tiled=True) / n_batch
                d_shared = tuple(jax.lax.psum(d, sib_axes) / n_batch
                                 for d in d_shared)
            return loss, (d_row[None, :], d_shared)

        loss, grads = run(param_arg, tuple(mb_leaves))
        return loss, grads

    return pipe_grad, prep.pack_params
