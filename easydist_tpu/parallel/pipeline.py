"""Pipeline parallelism as a single compiled SPMD program.

The reference implements PP with per-stage processes, eager NCCL P2P sends,
and Python schedule loops (easydist/torch/experimental/pp/runtime.py:113-700,
ScheduleGPipe :630, ScheduleDAPPLE :658).  On TPU the idiomatic design is a
single XLA program: every device runs the same `stage_fn` on its own stage's
weights (stacked on a leading stage axis sharded over the `pp` mesh axis),
activations rotate between neighbours with `lax.ppermute` inside a
`lax.scan` over pipeline ticks.  Autodiff through the scan yields the
backward pipeline automatically (ppermute transposes to the reverse
rotation), giving a GPipe-equivalent schedule; memory is controlled with
`jax.checkpoint` on the stage body (the XLA-era answer to 1F1B's
activation-memory motivation).

Requires homogeneous stages (transformer blocks) — heterogeneous first/last
layers (embedding, head) run outside the pipelined middle, which is how GPT
class models decompose naturally.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from easydist_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P


@dataclass
class PipelineConfig:
    n_stages: int
    n_microbatches: int
    axis_name: str = "pp"
    # "gpipe" keeps all microbatch activations (scan); "remat" wraps the
    # stage body in jax.checkpoint to trade recompute for memory; "1f1b"
    # (spmd_pipeline_grad only) interleaves forward and backward ticks so at
    # most O(n_stages) microbatch residuals are live per device — the
    # DAPPLE/1F1B working-set profile (reference runtime.py:658-700)
    schedule: str = "gpipe"
    # hybrid PPxSPMD (reference compile_auto.py:683-715 mesh
    # ['pp','spmd0','spmd1']): shard the microbatch dim over a data axis
    # and/or stage params over a tensor axis, all inside the same program
    data_axis: Optional[str] = None  # shards microbatches' batch dim
    param_spec: Optional[object] = None  # extra PartitionSpec tail for params
    # virtual stages per device (interleaved, Megatron-style): the model
    # is split into n_virtual * n_stages chunks; chunk j runs on device
    # j % n_stages and stage_params carry a LEADING DIM of
    # n_virtual * n_stages.  Shrinks the pipeline bubble ~1/n_virtual.
    # Used by spmd_pipeline (forward) and spmd_pipeline_grad ("1f1b").
    n_virtual: int = 1


def _stage_param_specs(stage_params, config: PipelineConfig, axis: str,
                       replicate_stage: bool = False):
    """PartitionSpecs for stage-stacked params: leading dim over `pp`,
    optionally a tensor-parallel tail spec (per-leaf or uniform).

    replicate_stage=True leaves the leading (stage) dim unsharded — used
    on hybrid pp x data meshes where resharding an inside-jit-produced
    stage stack into a pp-sharded shard_map input is miscompiled (see
    the data_axis note in spmd_pipeline); the pipeline bodies then slice
    their stage by `axis_index` instead of receiving a pre-sliced shard.
    The tensor-parallel tail specs are preserved either way."""
    lead = None if replicate_stage else axis
    if config.param_spec is None:
        return jax.tree_util.tree_map(lambda _: P(lead), stage_params)
    is_spec = lambda x: isinstance(x, (tuple, P))  # noqa: E731
    p_leaves, p_td = jax.tree_util.tree_flatten(stage_params)
    s_leaves, s_td = jax.tree_util.tree_flatten(config.param_spec,
                                                is_leaf=is_spec)
    if s_td == p_td:
        # per-leaf spec tails (pytree matching stage_params)
        specs = [P(lead, *tuple(t)) for t in s_leaves]
        return jax.tree_util.tree_unflatten(p_td, specs)
    tail = tuple(config.param_spec)
    return jax.tree_util.tree_map(lambda _: P(lead, *tail), stage_params)


def spmd_pipeline(stage_fn: Callable, mesh, config: PipelineConfig):
    """Build fn(stage_params, microbatches) -> outputs.

    stage_params: pytree with leading dim n_stages (or
    n_virtual * n_stages when interleaving; sharded over `pp`).
    microbatches: [n_microbatches, microbatch..., features] (replicated).
    Returns outputs of the last stage, same leading microbatch layout,
    replicated across the pp axis.
    """
    S = config.n_stages
    M = config.n_microbatches
    axis = config.axis_name
    if mesh.shape[axis] != S:
        raise ValueError(f"mesh axis {axis!r} has size {mesh.shape[axis]}, "
                         f"expected n_stages={S}")

    body = stage_fn
    if config.schedule == "remat":
        body = jax.checkpoint(stage_fn)
    if config.n_virtual > 1:
        return _interleaved_forward(body, mesh, config)

    from easydist_tpu import config as edconfig

    if edconfig.enable_analyze:
        from easydist_tpu.analyze import (check_schedule_tables,
                                          gpipe_schedule_tables)

        check_schedule_tables(gpipe_schedule_tables(S, M), S, 1, M,
                              fwd_only=True, node="pipeline/gpipe")

    def pipelined(stage_params, microbatches):
        # stage-stacked params shard their leading dim over pp (optionally
        # with a tensor-parallel tail spec); microbatches shard their batch
        # dim over the data axis when configured.
        #
        # data_axis caveat: on a multi-axis (pp x data) mesh, feeding a
        # stage stack PRODUCED INSIDE the surrounding jit into a
        # pp-sharded in_spec is miscompiled by GSPMD — the reshard into
        # the manual region inserts a spurious all-reduce over the data
        # axis, scaling every stage's params by the data-axis size
        # (repro: jit(lambda ps, x: pipe(jnp.stack(ps), x)) on a (4, 2)
        # mesh applies each stage bias twice; pre-stacked args are
        # unaffected).  Work around it by passing the stage dim
        # REPLICATED and slicing each device's stage by axis_index
        # inside the manual region — an all-gather resolves that
        # resharding correctly.
        rep_stage = config.data_axis is not None
        param_specs = _stage_param_specs(stage_params, config, axis,
                                         replicate_stage=rep_stage)
        data_spec = P(None, config.data_axis) if config.data_axis else P()

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(param_specs, data_spec),
                           out_specs=data_spec,
                           check_vma=False)
        def run(params, x_mb):
            stage_id = jax.lax.axis_index(axis)
            if rep_stage:
                local = jax.tree_util.tree_map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, stage_id, 0, keepdims=False), params)
            else:
                local = jax.tree_util.tree_map(lambda p: p[0], params)
            T = M + S - 1
            mb_shape = x_mb.shape[1:]
            state0 = jnp.zeros(mb_shape, x_mb.dtype)
            out0 = jnp.zeros_like(x_mb)

            def tick(carry, t):
                state_in, outputs = carry
                # stage 0 ingests microbatch t while t < M
                mb_idx = jnp.clip(t, 0, M - 1)
                fresh = x_mb[mb_idx]
                inp = jnp.where(stage_id == 0, fresh, state_in)
                out = body(local, inp)
                # last stage emits microbatch t-(S-1) once the fill ends
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                emit = jnp.logical_and(stage_id == S - 1, t >= S - 1)
                outputs = outputs.at[out_idx].set(
                    jnp.where(emit, out, outputs[out_idx]))
                nxt = jax.lax.ppermute(
                    out, axis, [(i, (i + 1) % S) for i in range(S)])
                return (nxt, outputs), None

            (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                           jnp.arange(T))
            # outputs live on the last stage only; replicate over pp
            outputs = jax.lax.psum(
                jnp.where(stage_id == S - 1, outputs, jnp.zeros_like(outputs)),
                axis)
            return outputs

        return run(stage_params, microbatches)

    return pipelined


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> single pytree with leading stage dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _virtual_params_and_specs(stage_params, config, axis, V, S,
                              replicate_stage: bool = False):
    """[V*S, ...] stage params regrouped to [V, S, ...] with specs sharding
    the S dim over pp (shared by the interleaved forward and 1F1B paths).
    replicate_stage leaves the S dim unsharded (the data_axis reshard
    workaround — see spmd_pipeline)."""
    vparams = jax.tree_util.tree_map(
        lambda p: p.reshape((V, S) + p.shape[1:]), stage_params)
    base_specs = _stage_param_specs(stage_params, config, axis,
                                    replicate_stage=replicate_stage)
    vspecs = jax.tree_util.tree_map(
        lambda sp: P(None, *tuple(sp)), base_specs,
        is_leaf=lambda x: isinstance(x, P))
    data_spec = P(None, config.data_axis) if config.data_axis else P()
    return vparams, vspecs, data_spec


def _interleaved_forward(body, mesh, config: PipelineConfig):
    """Forward pipeline with V interleaved virtual chunks per device
    (chunk j on device j % S): the fwd half of the 1F1B supertick tables,
    shrinking the fill bubble ~1/V for inference pipelines."""
    S, M, V = config.n_stages, config.n_microbatches, config.n_virtual
    axis = config.axis_name
    tables = _1f1b_schedule_tables(S, V, M, fwd_only=True)
    U = tables["n_superticks"]

    def pipelined(stage_params, microbatches):
        # rep_stage: the data_axis reshard workaround (see spmd_pipeline)
        rep_stage = config.data_axis is not None
        vparams, vspecs, data_spec = _virtual_params_and_specs(
            stage_params, config, axis, V, S, replicate_stage=rep_stage)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(vspecs, data_spec),
                           out_specs=data_spec, check_vma=False)
        def run(params, x_mb):
            tree = jax.tree_util
            s = jax.lax.axis_index(axis)
            if rep_stage:
                local = tree.tree_map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, s, 1, keepdims=False), params)  # [V, ...]
            else:
                local = tree.tree_map(lambda p: p[:, 0], params)  # [V, ...]
            MF, KF, FOK = (jnp.asarray(tables[k]) for k in
                           ("m_f", "k_f", "f_ok"))
            out0 = jnp.zeros_like(x_mb)
            zero_mb = jnp.zeros(x_mb.shape[1:], x_mb.dtype)

            def tick(carry, u):
                act_in, outputs = carry
                m_f, k_f, f_ok = MF[u, s], KF[u, s], FOK[u, s]
                local_f = tree.tree_map(lambda p: p[k_f], local)
                inp = jnp.where((s == 0) & (k_f == 0), x_mb[m_f], act_in)
                y = body(local_f, inp)
                emit = (s == S - 1) & (k_f == V - 1) & f_ok
                outputs = outputs.at[m_f].set(
                    jnp.where(emit, y, outputs[m_f]))
                act_out = jax.lax.ppermute(
                    y, axis, [(i, (i + 1) % S) for i in range(S)])
                return (act_out, outputs), None

            (_, outputs), _ = jax.lax.scan(tick, (zero_mb, out0),
                                           jnp.arange(U))
            return jax.lax.psum(
                jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs)),
                axis)

        return run(vparams, microbatches)

    return pipelined


def spmd_pipeline_grad(stage_fn: Callable, loss_fn: Callable, mesh,
                       config: PipelineConfig, aux: bool = False):
    """Build fn(stage_params, microbatches, targets) -> (loss, grads).

    loss = mean over microbatches of ``loss_fn(last_stage_out_mb, target_mb)``;
    grads match ``jax.grad`` of the equivalent non-pipelined step exactly.

    With ``aux=True`` the loss takes trailing parameters (a model head) and
    the pipeline also backpropagates to its inputs:
    ``loss_fn(out_mb, target_mb, loss_params)``; the built function becomes
    ``fn(stage_params, microbatches, targets, loss_params) ->
    (loss, stage_grads, dmicrobatches, dloss_params)`` — everything needed
    to embed the pipelined middle inside a larger model (embedding in front,
    head behind), reference compile_pipeline.py's full-model stage split.

    schedule="gpipe"/"remat": differentiate through the forward pipeline
    scan — all M microbatch residuals stay live through the backward sweep.

    schedule="1f1b": DAPPLE-class one-forward-one-backward (reference
    ScheduleDAPPLE, pp/runtime.py:658-700) re-designed as a single lockstep
    SPMD scan, the TPU-idiomatic form: every "supertick" each device runs
    one (masked) forward AND one (masked) backward, activations ppermute up
    the ring while gradients ppermute down, and XLA overlaps both transfers
    with compute.  Supertick clock: fwd(s, m) at u = s + m, bwd(s, m) at
    u = 2S - 2 - s + m, total U = M + 2S - 2 superticks.  Each stage keeps
    at most min(2S-1, M) microbatches of vjp residuals in a ring buffer —
    the 1F1B O(n_stages) working set — versus gpipe's O(M).  Residual
    leaves that are just the (tick-invariant) stage params are detected by
    tracer identity and NOT stored per-slot.  In steady state every device
    does one full fwd + bwd of useful work per supertick, so the bubble is
    2(2S-2) supertick-halves against gpipe's 2(S-1) — the classic 1F1B
    trade: slightly wider bubble bound, O(S) instead of O(M) memory, no
    recompute (unlike schedule="remat").
    """
    S = config.n_stages
    M = config.n_microbatches
    axis = config.axis_name
    if mesh.shape[axis] != S:
        raise ValueError(f"mesh axis {axis!r} has size {mesh.shape[axis]}, "
                         f"expected n_stages={S}")

    if config.schedule in ("gpipe", "remat"):
        fwd_pipe = spmd_pipeline(stage_fn, mesh, config)

        if aux:
            def pipelined(stage_params, microbatches, targets, loss_params):
                def total_loss(sp, mbs, lp):
                    outs = fwd_pipe(sp, mbs)
                    return jnp.mean(jax.vmap(
                        lambda o, t: loss_fn(o, t, lp))(outs, targets))

                loss, (dsp, dmb, dlp) = jax.value_and_grad(
                    total_loss, argnums=(0, 1, 2))(
                        stage_params, microbatches, loss_params)
                return loss, dsp, dmb, dlp
        else:
            def pipelined(stage_params, microbatches, targets):
                def total_loss(sp):
                    outs = fwd_pipe(sp, microbatches)
                    return jnp.mean(jax.vmap(loss_fn)(outs, targets))

                return jax.value_and_grad(total_loss)(stage_params)

        return pipelined

    if config.schedule != "1f1b":
        raise ValueError(f"unknown schedule {config.schedule!r}")

    body = stage_fn
    V = max(1, config.n_virtual)
    tables = _1f1b_schedule_tables(S, V, M)
    R = tables["ring"]
    U = tables["n_superticks"]
    loss3 = loss_fn if aux else (lambda o, t, lp: loss_fn(o, t))

    def pipelined(stage_params, microbatches, targets, loss_params=None):
        lp_in = loss_params if aux else ()
        # stage-stacked params [V*S, ...] regrouped to [V, S, ...]: chunk k
        # of device s is global stage k*S + s.  With a data axis the
        # params enter REPLICATED over the stage dim and each device
        # slices its stage by axis_index (the data_axis reshard
        # workaround — see spmd_pipeline); the grads still leave
        # stage-SHARDED, so the output spec keeps the pp-sharded form.
        rep_stage = config.data_axis is not None
        vparams, vspecs, data_spec = _virtual_params_and_specs(
            stage_params, config, axis, V, S)
        vspecs_in = vspecs
        if rep_stage:
            _, vspecs_in, _ = _virtual_params_and_specs(
                stage_params, config, axis, V, S, replicate_stage=True)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(vspecs_in, data_spec, data_spec, P()),
                           out_specs=(P(), vspecs, data_spec, P()),
                           check_vma=False)
        def run(params, x_mb, tgt_mb, lp):
            tree = jax.tree_util
            s = jax.lax.axis_index(axis)
            if rep_stage:
                local = tree.tree_map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, s, 1, keepdims=False), params)  # [V, ...]
            else:
                local = tree.tree_map(lambda p: p[:, 0], params)  # [V, ...]
            mb_shape = x_mb.shape[1:]

            MF, KF, FOK = (jnp.asarray(tables[k]) for k in
                           ("m_f", "k_f", "f_ok"))
            MB, KB, BOK = (jnp.asarray(tables[k]) for k in
                           ("m_b", "k_b", "b_ok"))

            # Probe the vjp residual structure once (dead code after trace:
            # only the treedef and which-leaves-are-params survive).  Leaves
            # that ARE a chunk-param leaf (tracer identity) are rebuilt from
            # `local` at backward time instead of being stored per ring slot.
            local0 = tree.tree_map(lambda p: p[0], local)
            probe_leaves = tree.tree_leaves(local0)
            _, vjp0 = jax.vjp(body, local0, jnp.zeros(mb_shape, x_mb.dtype))
            leaves0, res_tree = tree.tree_flatten(vjp0)
            shared_idx = [
                next((j for j, q in enumerate(probe_leaves) if l is q), -1)
                for l in leaves0]
            store_idx = [i for i, si in enumerate(shared_idx) if si < 0]
            rings0 = [jnp.zeros((V, R) + tuple(leaves0[i].shape),
                                leaves0[i].dtype) for i in store_idx]

            zero_mb = jnp.zeros(mb_shape, x_mb.dtype)
            dacc0 = tree.tree_map(jnp.zeros_like, local)
            dxs0 = jnp.zeros_like(x_mb)
            dlp0 = tree.tree_map(jnp.zeros_like, lp)

            def tick(carry, u):
                act_in, g_in, rings, dacc, lacc, dxs, dlp_acc = carry

                # ---- forward half
                m_f, k_f, f_ok = MF[u, s], KF[u, s], FOK[u, s]
                local_f = tree.tree_map(lambda p: p[k_f], local)
                inp = jnp.where((s == 0) & (k_f == 0), x_mb[m_f], act_in)
                y, vjp = jax.vjp(body, local_f, inp)
                leaves = tree.tree_flatten(vjp)[0]
                slot_f = m_f % R
                rings = [
                    r.at[k_f, slot_f].set(
                        jnp.where(f_ok, leaves[i], r[k_f, slot_f]))
                    for r, i in zip(rings, store_idx)]

                # the final chunk's stage turns around in the same
                # supertick: loss grad of THIS microbatch feeds its
                # backward.  The head loss (+vjp) can be as heavy as a
                # stage (GPT logits at vocab scale), so gate it behind a
                # per-device conditional — only the last stage's turnaround
                # ticks pay it.  (loss_fn must not contain collectives.)
                m_b, k_b, b_ok = MB[u, s], KB[u, s], BOK[u, s]
                # stage S-1 chunk V-1 has fwd and bwd of one microbatch in
                # the same supertick, so one predicate covers lval, g, dlp
                pred = (s == S - 1) & (k_f == V - 1) & f_ok

                def loss_branch(args):
                    y_, t_, lp_ = args
                    lval, loss_vjp = jax.vjp(loss3, y_, t_, lp_)
                    g_, _, dlp_ = loss_vjp(jnp.ones_like(lval) / M)
                    return jnp.float32(lval), g_, dlp_

                def zero_branch(args):
                    y_, _, lp_ = args
                    return (jnp.float32(0.0), jnp.zeros_like(y_),
                            tree.tree_map(jnp.zeros_like, lp_))

                lval, g_last, dlp_t = jax.lax.cond(
                    pred, loss_branch, zero_branch, (y, tgt_mb[m_b], lp))
                g = jnp.where(pred, g_last, g_in)
                lacc = lacc + lval
                dlp_acc = tree.tree_map(lambda a, d: a + d, dlp_acc, dlp_t)

                # ---- backward half: rebuild the saved vjp and apply it
                local_b = tree.tree_map(lambda p: p[k_b], local)
                pl_b = tree.tree_leaves(local_b)
                slot_b = m_b % R
                stored = iter(range(len(store_idx)))
                rebuilt = [
                    pl_b[shared_idx[i]] if shared_idx[i] >= 0
                    else rings[next(stored)][k_b, slot_b]
                    for i in range(len(leaves))]
                dp, dx = tree.tree_unflatten(res_tree, rebuilt)(g)
                dacc = tree.tree_map(
                    lambda a, d: a.at[k_b].add(jnp.where(b_ok, d, 0)),
                    dacc, dp)
                # pipeline-input grads surface at stage 0's chunk-0 backward
                dxs = dxs.at[m_b].set(jnp.where(
                    (s == 0) & (k_b == 0) & b_ok, dx, dxs[m_b]))

                # activations ride up the ring, gradients ride down
                act_out = jax.lax.ppermute(
                    y, axis, [(i, (i + 1) % S) for i in range(S)])
                g_out = jax.lax.ppermute(
                    dx, axis, [(i, (i - 1) % S) for i in range(S)])
                return (act_out, g_out, rings, dacc, lacc, dxs, dlp_acc), None

            carry0 = (zero_mb, zero_mb, rings0, dacc0, jnp.float32(0.0),
                      dxs0, dlp0)
            (_, _, _, dacc, lacc, dxs, dlp_acc), _ = jax.lax.scan(
                tick, carry0, jnp.arange(U))

            loss = jax.lax.psum(
                jnp.where(s == S - 1, lacc, 0.0), axis) / M
            # input grads live on stage 0, head grads on the last stage;
            # replicate both across pp
            dxs = jax.lax.psum(dxs, axis)
            dlp_acc = tree.tree_map(lambda d: jax.lax.psum(d, axis), dlp_acc)
            if config.data_axis:
                loss = jax.lax.pmean(loss, config.data_axis)
                dacc = tree.tree_map(
                    lambda d: jax.lax.pmean(d, config.data_axis), dacc)
                dlp_acc = tree.tree_map(
                    lambda d: jax.lax.pmean(d, config.data_axis), dlp_acc)
                # input grads stay per-shard but must reflect the GLOBAL
                # mean loss: d(mean of shard means)/dx = (1/dp) d(local)/dx
                dxs = dxs / mesh.shape[config.data_axis]
            grads = tree.tree_map(lambda d: d[:, None], dacc)
            return loss, grads, dxs, dlp_acc

        loss, vgrads, dxs, dlp = run(vparams, microbatches, targets, lp_in)
        grads = jax.tree_util.tree_map(
            lambda g, p: g.reshape(p.shape), vgrads, stage_params)
        if aux:
            return loss, grads, dxs, dlp
        return loss, grads

    return pipelined


def _1f1b_schedule_tables(S: int, V: int, M: int,
                          fwd_only: bool = False):
    """Host-side supertick schedule for (interleaved) 1F1B.

    Global stage j = k*S + s (chunk k on device s), J = V*S stages.
    Microbatches run in groups of S (Megatron interleaving):
      fwd(j, m) at u = j + (m % S) + (m // S) * V*S
      bwd(j, m) at u = (2J - 2 - j) + (m % S) + (m // S) * V*S
    Consecutive stages are exactly one supertick apart (device +1 ring for
    activations, -1 for grads), each device has at most one fwd and one bwd
    unit per supertick, and the final chunk's last stage turns a microbatch
    around within its own supertick.  Returns [U, S] int32/bool lookup
    tables plus the residual ring size (max in-flight microbatches per
    (device, chunk) — the O(S·V) 1F1B working set).
    """
    import numpy as np

    J = V * S
    stride = V * S

    def u_f(j, m):
        return j + (m % S) + (m // S) * stride

    def u_b(j, m):
        return (2 * J - 2 - j) + (m % S) + (m // S) * stride

    U = u_f(J - 1, M - 1) + 1 if fwd_only else u_b(0, M - 1) + 1
    m_f = np.zeros((U, S), np.int32)
    k_f = np.zeros((U, S), np.int32)
    f_ok = np.zeros((U, S), bool)
    m_b = np.zeros((U, S), np.int32)
    k_b = np.zeros((U, S), np.int32)
    b_ok = np.zeros((U, S), bool)
    ring = 1
    for s in range(S):
        for k in range(V):
            j = k * S + s
            for m in range(M):
                uf = u_f(j, m)
                assert not f_ok[uf, s], "fwd slot conflict"
                m_f[uf, s], k_f[uf, s], f_ok[uf, s] = m, k, True
                if fwd_only:
                    continue
                ub = u_b(j, m)
                assert not b_ok[ub, s], "bwd slot conflict"
                m_b[ub, s], k_b[ub, s], b_ok[ub, s] = m, k, True
            if fwd_only:
                continue
            # max in-flight microbatches for this (device, chunk): FIFO, so
            # the live set is a contiguous m-window and `m % ring` is unique
            live = max(
                sum(1 for m2 in range(M) if u_f(j, m2) <= u_b(j, m1))
                - m1 for m1 in range(M))
            ring = max(ring, live)
    tables = {"m_f": m_f, "k_f": k_f, "f_ok": f_ok,
              "m_b": m_b, "k_b": k_b, "b_ok": b_ok,
              "n_superticks": U, "ring": ring}

    # build-time schedule lint (easydist_tpu.analyze SCHED rules): the
    # lockstep scan runs masked garbage ticks rather than crashing on a
    # bad table, so dependency/stash bugs must be caught HERE
    from easydist_tpu import config as edconfig

    if edconfig.enable_analyze:
        from easydist_tpu.analyze import check_schedule_tables

        check_schedule_tables(
            tables, S, V, M, fwd_only=fwd_only,
            node="pipeline/interleaved-fwd" if fwd_only
            else "pipeline/1f1b")
    return tables
