"""Pipeline parallelism as a single compiled SPMD program.

The reference implements PP with per-stage processes, eager NCCL P2P sends,
and Python schedule loops (easydist/torch/experimental/pp/runtime.py:113-700,
ScheduleGPipe :630, ScheduleDAPPLE :658).  On TPU the idiomatic design is a
single XLA program: every device runs the same `stage_fn` on its own stage's
weights (stacked on a leading stage axis sharded over the `pp` mesh axis),
activations rotate between neighbours with `lax.ppermute` inside a
`lax.scan` over pipeline ticks.  Autodiff through the scan yields the
backward pipeline automatically (ppermute transposes to the reverse
rotation), giving a GPipe-equivalent schedule; memory is controlled with
`jax.checkpoint` on the stage body (the XLA-era answer to 1F1B's
activation-memory motivation).

Requires homogeneous stages (transformer blocks) — heterogeneous first/last
layers (embedding, head) run outside the pipelined middle, which is how GPT
class models decompose naturally.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P


@dataclass
class PipelineConfig:
    n_stages: int
    n_microbatches: int
    axis_name: str = "pp"
    # "gpipe" keeps all microbatch activations (scan); "remat" wraps the
    # stage body in jax.checkpoint to trade recompute for memory
    schedule: str = "gpipe"
    # hybrid PPxSPMD (reference compile_auto.py:683-715 mesh
    # ['pp','spmd0','spmd1']): shard the microbatch dim over a data axis
    # and/or stage params over a tensor axis, all inside the same program
    data_axis: Optional[str] = None  # shards microbatches' batch dim
    param_spec: Optional[object] = None  # extra PartitionSpec tail for params


def spmd_pipeline(stage_fn: Callable, mesh, config: PipelineConfig):
    """Build fn(stage_params, microbatches) -> outputs.

    stage_params: pytree with leading dim n_stages (sharded over `pp`).
    microbatches: [n_microbatches, microbatch..., features] (replicated).
    Returns outputs of the last stage, same leading microbatch layout,
    replicated across the pp axis.
    """
    S = config.n_stages
    M = config.n_microbatches
    axis = config.axis_name
    if mesh.shape[axis] != S:
        raise ValueError(f"mesh axis {axis!r} has size {mesh.shape[axis]}, "
                         f"expected n_stages={S}")

    body = stage_fn
    if config.schedule == "remat":
        body = jax.checkpoint(stage_fn)

    def pipelined(stage_params, microbatches):
        # stage-stacked params shard their leading dim over pp (optionally
        # with a tensor-parallel tail spec); microbatches shard their batch
        # dim over the data axis when configured
        if config.param_spec is None:
            param_specs = jax.tree_util.tree_map(lambda _: P(axis),
                                                 stage_params)
        else:
            is_spec = lambda x: isinstance(x, (tuple, P))  # noqa: E731
            p_leaves, p_td = jax.tree_util.tree_flatten(stage_params)
            s_leaves, s_td = jax.tree_util.tree_flatten(config.param_spec,
                                                        is_leaf=is_spec)
            if s_td == p_td:
                # per-leaf spec tails (pytree matching stage_params)
                specs = [P(axis, *tuple(t)) for t in s_leaves]
                param_specs = jax.tree_util.tree_unflatten(p_td, specs)
            else:
                tail = tuple(config.param_spec)
                param_specs = jax.tree_util.tree_map(
                    lambda _: P(axis, *tail), stage_params)
        data_spec = P(None, config.data_axis) if config.data_axis else P()

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(param_specs, data_spec),
                           out_specs=data_spec,
                           check_vma=False)
        def run(params, x_mb):
            stage_id = jax.lax.axis_index(axis)
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            T = M + S - 1
            mb_shape = x_mb.shape[1:]
            state0 = jnp.zeros(mb_shape, x_mb.dtype)
            out0 = jnp.zeros_like(x_mb)

            def tick(carry, t):
                state_in, outputs = carry
                # stage 0 ingests microbatch t while t < M
                mb_idx = jnp.clip(t, 0, M - 1)
                fresh = x_mb[mb_idx]
                inp = jnp.where(stage_id == 0, fresh, state_in)
                out = body(local, inp)
                # last stage emits microbatch t-(S-1) once the fill ends
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                emit = jnp.logical_and(stage_id == S - 1, t >= S - 1)
                outputs = outputs.at[out_idx].set(
                    jnp.where(emit, out, outputs[out_idx]))
                nxt = jax.lax.ppermute(
                    out, axis, [(i, (i + 1) % S) for i in range(S)])
                return (nxt, outputs), None

            (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                           jnp.arange(T))
            # outputs live on the last stage only; replicate over pp
            outputs = jax.lax.psum(
                jnp.where(stage_id == S - 1, outputs, jnp.zeros_like(outputs)),
                axis)
            return outputs

        return run(stage_params, microbatches)

    return pipelined


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> single pytree with leading stage dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)
