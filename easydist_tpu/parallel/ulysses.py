"""Ulysses-style sequence parallelism: head<->sequence all_to_all.

Absent from the reference (SURVEY.md §2.9).  Inputs arrive sequence-sharded;
an `all_to_all` regroups to head-sharded full-sequence tensors so each device
runs ordinary full attention on heads/n heads, then a second all_to_all
returns to sequence sharding.  Two all_to_alls per attention vs ring's n-1
ppermutes — better for moderate sequence lengths on fat ICI.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from easydist_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P


def _full_attention(q, k, v, causal: bool, scale: float):
    # single source of truth for the reference attention math
    from easydist_tpu.ops.attention_prim import _einsum_attention

    return _einsum_attention(q, k, v, causal, scale)


def ulysses_attention(q, k, v, mesh, axis: str = "sp", causal: bool = True,
                      scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None):
    """q,k,v: [batch, heads, seq, head_dim] sequence-sharded over `axis`.
    heads must be divisible by the axis size."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if attn_fn is None:
        def attn_fn(q_, k_, v_):
            return _full_attention(q_, k_, v_, causal, scale)

    def local(q_, k_, v_):
        # [b, h, t/n, d] -> all_to_all -> [b, h/n, t, d]
        def seq2head(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = seq2head(q_), seq2head(k_), seq2head(v_)
        out = attn_fn(qh, kh, vh)
        return head2seq(out)

    spec = P(None, None, axis, None)
    # manual ONLY over `axis` (sibling mesh axes stay GSPMD-auto; see
    # ring_attention)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names=frozenset({axis}),
                     check_vma=False)(q, k, v)
