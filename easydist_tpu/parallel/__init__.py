"""Manual parallelism building blocks (the TPU-native equivalents of the
reference's pp/, compile_dp, and the missing-in-reference long-context and
MoE support — SURVEY.md §2.9 requires SP/CP/EP as first-class here).

Everything is expressed as compiled collective programs (`shard_map` +
`ppermute`/`all_to_all`/`psum`) inside one XLA program — no eager P2P.
"""

from .pipeline import (spmd_pipeline, spmd_pipeline_grad,  # noqa: F401
                       PipelineConfig)
from .dp import ddp_step, zero_shard_params, zero2_step, zero3_step  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .auto_pipeline import pipeline_forward, split_point  # noqa: F401
