"""Ring attention: exact attention over sequence-sharded Q/K/V.

Absent from the reference (SURVEY.md §2.9: context parallel / ring attention
"Absent") and required here as a first-class long-context capability.  Each
device holds a sequence chunk of Q, K, V; K/V blocks rotate around the ICI
ring with `lax.ppermute` while a flash-style online softmax accumulates the
exact result — memory per device is O(seq/n), communication overlaps with
the block computation, and the whole thing is one compiled XLA program.

Layout: [batch, heads, seq_shard, head_dim] inside `shard_map` over the
sequence mesh axis.  Causal masking uses global positions derived from the
device's ring index.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from easydist_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P


def _block_attn(q, k, v, mask, scale):
    """One block: returns (unnormalized out, running max, running denom)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    s = jnp.where(mask, s, jnp.array(-1e30, s.dtype))
    m = jnp.max(s, axis=-1)  # [b,h,q]
    # rows with no visible keys: keep m finite so exp() is well-defined
    m_safe = jnp.maximum(m, -1e30 / 2)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m_safe, l


def _ring_attention_local(q, k, v, axis: str, causal: bool, scale: float,
                          block_impl: str = "einsum"):
    n = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    t_local = q.shape[2]

    q_pos = idx * t_local + jnp.arange(t_local)

    def flash_block(q_, k_blk, v_blk, src):
        """Pallas flash kernel as the per-block compute: its normalized
        output + logsumexp form a valid (o, m, l=1) triple for the online
        merge (o_norm = o_raw/l and lse = m + log l).  flash_attention_lse
        is a custom_vjp in both outputs, so the ring stays differentiable."""
        from easydist_tpu.ops.flash_attention import flash_attention_lse

        b, h, t, _ = q_.shape

        def run(block_causal):
            out, lse = flash_attention_lse(q_, k_blk, v_blk, block_causal,
                                           scale)
            return out.astype(jnp.float32), lse.reshape(b, h, t)

        if causal:
            out_b, lse_b = jax.lax.cond(
                src == idx, lambda _: run(True), lambda _: run(False), None)
            visible = src <= idx  # src > idx: block fully in the future
            m_b = jnp.where(visible, lse_b, -1e30 / 2)
            l_b = jnp.where(visible, 1.0, 0.0) * jnp.ones_like(lse_b)
            o_b = jnp.where(visible, out_b, 0.0)
        else:
            o_b, m_b = run(False)
            l_b = jnp.ones_like(m_b)
        return o_b, m_b, l_b

    def step(carry, r):
        o_acc, m_acc, l_acc, k_blk, v_blk = carry
        # block r came from device (idx - r) mod n
        src = jnp.mod(idx - r, n)
        if block_impl == "flash":
            o_b, m_b, l_b = flash_block(q, k_blk, v_blk, src)
        else:
            k_pos = src * t_local + jnp.arange(t_local)
            if causal:
                mask = k_pos[None, None, None, :] <= q_pos[None, None, :,
                                                           None]
            else:
                mask = jnp.ones((1, 1, t_local, t_local), bool)
            # rotate k/v in their input dtype (half the ICI bytes for
            # bf16); accumulate in f32 per block
            o_b, m_b, l_b = _block_attn(q, k_blk.astype(jnp.float32),
                                        v_blk.astype(jnp.float32), mask,
                                        scale)

        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        o_acc = o_acc * alpha[..., None] + o_b * beta[..., None]
        l_acc = l_acc * alpha + l_b * beta

        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (o_acc, m_new, l_acc, k_blk, v_blk), None

    b, h, t, d = q.shape
    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t), -1e30 / 2, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(step, (o0, m0, l0, k, v),
                                      jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   block_impl: Optional[str] = None):
    """Exact attention with q/k/v sequence-sharded over mesh axis `axis`.

    q, k, v: [batch, heads, seq, head_dim] global arrays (seq divisible by
    the axis size).  Returns [batch, heads, seq, head_dim] sharded the same.

    block_impl: per-device block compute — "flash" (Pallas kernel, O(t/n)
    block memory) or "einsum".  None auto-selects flash on TPU.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if block_impl is None:
        block_impl = "flash" if jax.default_backend() == "tpu" else "einsum"
    fn = functools.partial(_ring_attention_local, axis=axis, causal=causal,
                           scale=scale, block_impl=block_impl)
    spec = P(None, None, axis, None)
    # manual ONLY over `axis`: other mesh axes stay GSPMD-auto, so a batch
    # or head sharding chosen on a sibling axis (hybrid dp x sp) survives
    # into the block compute instead of being forced replicated
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names=frozenset({axis}),
                     check_vma=False)(q, k, v)
