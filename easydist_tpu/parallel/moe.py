"""Mixture-of-Experts with expert parallelism (EP).

Absent from the reference (SURVEY.md §2.9: "Expert parallel (EP / MoE) —
Absent") and first-class here.  Switch-style top-1 routing with capacity
buffers, GShard-style dense dispatch (einsum with one-hot masks — MXU
friendly, no dynamic shapes), experts sharded over the `ep` mesh axis, and
token exchange via `lax.all_to_all` inside one compiled program.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P


@dataclass
class MoEConfig:
    n_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 1.25


def moe_init(cfg: MoEConfig, key) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(k1, (cfg.d_model, cfg.n_experts)) * 0.02,
        "w_in": jax.random.normal(k2, (cfg.n_experts, cfg.d_model, cfg.d_ff))
                / math.sqrt(cfg.d_model),
        "w_out": jax.random.normal(k3, (cfg.n_experts, cfg.d_ff, cfg.d_model))
                 / math.sqrt(cfg.d_ff),
    }


def _moe_local(x, router, w_in, w_out, *, axis: str, n_experts: int,
               capacity: int):
    """x: [n_local, d]; w_in/w_out: [E/n, ...] local expert shards."""
    n_local, d = x.shape
    ep = jax.lax.psum(1, axis)

    logits = x @ router  # [n, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [n]
    gate = jnp.max(probs, axis=-1)  # [n]

    onehot = jax.nn.one_hot(expert, n_experts, dtype=x.dtype)  # [n, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) - 1.0  # [n, E]
    pos_tok = jnp.sum(pos * onehot, axis=-1)  # [n]
    keep = pos_tok < capacity
    gate = gate * keep

    pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                            dtype=x.dtype)  # [n, C]
    dispatch = onehot[:, :, None] * pos_oh[:, None, :] * keep[:, None, None]
    # [n, E, C] -> buffers [E, C, d]
    buffers = jnp.einsum("nec,nd->ecd", dispatch, x)

    # exchange: every device sends its per-expert buffers to the expert
    # owner; E splits across devices, capacity concatenates
    buffers = jax.lax.all_to_all(buffers, axis, split_axis=0, concat_axis=1,
                                 tiled=True)  # [E/ep, C*ep, d]

    h = jnp.einsum("ecd,edf->ecf", buffers, w_in)
    h = jax.nn.gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, w_out)  # [E/ep, C*ep, d]

    out = jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                             tiled=True)  # [E, C, d]
    y = jnp.einsum("nec,ecd->nd", dispatch, out) * gate[:, None]

    # Switch load-balancing loss: E * sum_e frac_tokens_e * mean_prob_e,
    # averaged over devices
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob)
    aux = jax.lax.pmean(aux, axis)
    return y, aux


def moe_layer(params: Dict, x, mesh, cfg: MoEConfig,
              axis: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """x: [tokens, d_model] (token dim sharded over `axis`); experts sharded
    over `axis`.  Returns (output [tokens, d_model], aux_loss scalar)."""
    ep = mesh.shape[axis]
    if cfg.n_experts % ep != 0:
        raise ValueError(f"n_experts {cfg.n_experts} not divisible by "
                         f"ep axis size {ep}")
    n_tokens = x.shape[0]
    n_local = n_tokens // ep
    capacity = max(1, int(math.ceil(n_local * cfg.capacity_factor
                                    / cfg.n_experts)))

    fn = shard_map(
        lambda xl, r, wi, wo: _moe_local(
            xl, r, wi, wo, axis=axis, n_experts=cfg.n_experts,
            capacity=capacity),
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False)
    return fn(x, params["router"], params["w_in"], params["w_out"])


def moe_reference(params: Dict, x, cfg: MoEConfig, n_devices: int = 1):
    """Single-device semantics-equivalent reference (same capacity limits per
    source shard) used by tests."""
    n = x.shape[0]
    n_local = n // n_devices
    capacity = max(1, int(math.ceil(n_local * cfg.capacity_factor
                                    / cfg.n_experts)))
    ys = []
    auxes = []
    for s in range(n_devices):
        xs = x[s * n_local:(s + 1) * n_local]
        logits = xs @ params["router"]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)
        gate = jnp.max(probs, axis=-1)
        onehot = jax.nn.one_hot(expert, cfg.n_experts, dtype=x.dtype)
        pos = jnp.cumsum(onehot, axis=0) - 1.0
        pos_tok = jnp.sum(pos * onehot, axis=-1)
        keep = pos_tok < capacity
        gate = gate * keep
        out = jnp.zeros_like(xs)
        for i in range(xs.shape[0]):
            e = int(expert[i])
            h = jax.nn.gelu(xs[i] @ params["w_in"][e])
            out = out.at[i].set(h @ params["w_out"][e])
        ys.append(out * gate[:, None])
        frac = jnp.mean(onehot, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        auxes.append(cfg.n_experts * jnp.sum(frac * mean_prob))
    return jnp.concatenate(ys), jnp.mean(jnp.stack(auxes))
