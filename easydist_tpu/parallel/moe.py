"""Mixture-of-Experts with expert parallelism (EP).

Absent from the reference (SURVEY.md §2.9: "Expert parallel (EP / MoE) —
Absent") and first-class here.  Switch-style top-1 routing with capacity
buffers, GShard-style dense dispatch (einsum with one-hot masks — MXU
friendly, no dynamic shapes), experts sharded over the `ep` mesh axis, and
token exchange via `lax.all_to_all` inside one compiled program.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from easydist_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P


@dataclass
class MoEConfig:
    n_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 1.25
    # experts per token: 1 = Switch routing, 2 = GShard-style top-2 (gates
    # renormalized over the selected experts)
    top_k: int = 1


def moe_init(cfg: MoEConfig, key) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(k1, (cfg.d_model, cfg.n_experts)) * 0.02,
        "w_in": jax.random.normal(k2, (cfg.n_experts, cfg.d_model, cfg.d_ff))
                / math.sqrt(cfg.d_model),
        "w_out": jax.random.normal(k3, (cfg.n_experts, cfg.d_ff, cfg.d_model))
                 / math.sqrt(cfg.d_ff),
    }


def _routing(probs, n_experts: int, capacity: int, top_k: int, dtype):
    """Top-k routing with per-expert capacity shared across slots.

    Returns (dispatch [n, E, C] summed over slots, per-slot combine
    weights as a list of ([n, E, C] dispatch_s, gate_s [n]) pairs,
    onehot_all [n, E] for the aux loss).
    """
    topk_probs, topk_idx = jax.lax.top_k(probs, top_k)  # [n, k]
    if top_k == 1:
        gates = topk_probs  # Switch: gate by the raw router probability
    else:
        gates = topk_probs / jnp.maximum(
            jnp.sum(topk_probs, axis=-1, keepdims=True), 1e-9)

    counts = jnp.zeros((probs.shape[1],), probs.dtype)  # filled per expert
    slot_dispatch = []
    onehot_all = jnp.zeros_like(probs)
    for s in range(top_k):
        onehot = jax.nn.one_hot(topk_idx[:, s], n_experts, dtype=dtype)
        pos = counts[None, :] + jnp.cumsum(onehot, axis=0) - 1.0
        pos_tok = jnp.sum(pos * onehot, axis=-1)
        keep = pos_tok < capacity
        pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                                dtype=dtype)
        disp = onehot[:, :, None] * pos_oh[:, None, :] * keep[:, None, None]
        slot_dispatch.append((disp, gates[:, s] * keep))
        counts = counts + jnp.sum(onehot * keep[:, None], axis=0)
        onehot_all = onehot_all + onehot
    dispatch = sum(d for d, _ in slot_dispatch)
    return dispatch, slot_dispatch, onehot_all


def _moe_local(x, router, w_in, w_out, *, axis: str, n_experts: int,
               capacity: int, top_k: int = 1):
    """x: [n_local, d]; w_in/w_out: [E/n, ...] local expert shards."""
    n_local, d = x.shape
    ep = jax.lax.psum(1, axis)

    logits = x @ router  # [n, E]
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, slot_dispatch, onehot = _routing(probs, n_experts, capacity,
                                               top_k, x.dtype)
    # [n, E, C] -> buffers [E, C, d]
    buffers = jnp.einsum("nec,nd->ecd", dispatch, x)

    # exchange: every device sends its per-expert buffers to the expert
    # owner; E splits across devices, capacity concatenates
    buffers = jax.lax.all_to_all(buffers, axis, split_axis=0, concat_axis=1,
                                 tiled=True)  # [E/ep, C*ep, d]

    h = jnp.einsum("ecd,edf->ecf", buffers, w_in)
    h = jax.nn.gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, w_out)  # [E/ep, C*ep, d]

    out = jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                             tiled=True)  # [E, C, d]
    y = sum(jnp.einsum("nec,ecd->nd", disp, out) * gate_s[:, None]
            for disp, gate_s in slot_dispatch)

    # Switch load-balancing loss: E * sum_e frac_tokens_e * mean_prob_e,
    # averaged over devices (assignment fractions normalized by top_k)
    frac = jnp.mean(onehot, axis=0) / max(top_k, 1)
    mean_prob = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob)
    aux = jax.lax.pmean(aux, axis)
    return y, aux


def moe_layer(params: Dict, x, mesh, cfg: MoEConfig,
              axis: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """x: [tokens, d_model] (token dim sharded over `axis`); experts sharded
    over `axis`.  Returns (output [tokens, d_model], aux_loss scalar)."""
    ep = mesh.shape[axis]
    if cfg.n_experts % ep != 0:
        raise ValueError(f"n_experts {cfg.n_experts} not divisible by "
                         f"ep axis size {ep}")
    n_tokens = x.shape[0]
    n_local = n_tokens // ep
    capacity = max(1, int(math.ceil(n_local * cfg.top_k
                                    * cfg.capacity_factor / cfg.n_experts)))

    fn = shard_map(
        lambda xl, r, wi, wo: _moe_local(
            xl, r, wi, wo, axis=axis, n_experts=cfg.n_experts,
            capacity=capacity, top_k=cfg.top_k),
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False)
    return fn(x, params["router"], params["w_in"], params["w_out"])


def moe_reference(params: Dict, x, cfg: MoEConfig, n_devices: int = 1):
    """Single-device semantics-equivalent reference (per-token python loop,
    same slot-major capacity accounting as `_routing`) used by tests."""
    import numpy as np

    n = x.shape[0]
    n_local = n // n_devices
    capacity = max(1, int(math.ceil(n_local * cfg.top_k
                                    * cfg.capacity_factor / cfg.n_experts)))
    ys = []
    auxes = []
    for s in range(n_devices):
        xs = x[s * n_local:(s + 1) * n_local]
        probs = np.asarray(jax.nn.softmax(xs @ params["router"], axis=-1))
        order = np.argsort(-probs, axis=-1)[:, :cfg.top_k]  # [n, k]
        topk = np.take_along_axis(probs, order, axis=-1)
        if cfg.top_k == 1:
            gates = topk
        else:
            gates = topk / np.maximum(topk.sum(-1, keepdims=True), 1e-9)

        counts = np.zeros(cfg.n_experts, np.int64)
        out = jnp.zeros_like(xs)
        onehot_frac = np.zeros(cfg.n_experts)
        for k in range(cfg.top_k):
            for i in range(xs.shape[0]):
                e = int(order[i, k])
                onehot_frac[e] += 1
                if counts[e] >= capacity:
                    continue
                counts[e] += 1
                h = jax.nn.gelu(xs[i] @ params["w_in"][e])
                out = out.at[i].add((h @ params["w_out"][e])
                                    * gates[i, k])
        ys.append(out)
        frac = onehot_frac / xs.shape[0] / max(cfg.top_k, 1)
        auxes.append(cfg.n_experts * jnp.sum(jnp.asarray(frac)
                                             * jnp.mean(probs, axis=0)))
    return jnp.concatenate(ys), jnp.mean(jnp.stack(auxes))
