"""Manual data-parallel and ZeRO modes (reference: easydist/torch/compile_dp.py).

The auto solver discovers DP on its own; these wrappers are the explicit
`parallel_mode="ddp"/"zero2"/"zero3"` equivalents (compile_dp.py:55-198),
expressed as sharding annotations + shard_map collectives instead of graph
surgery over NCCL ops:

  ddp    — batch sharded, params replicated, grads pmean'd
  zero2  — + optimizer state sharded over dp: reduce_scatter grads, update
           the local shard, all_gather updated params
  zero3  — fully sharded params too: handled by running zero2 with params
           stored sharded and gathered inside the step (XLA does the
           gather/free scheduling)
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P


def ddp_step(loss_fn: Callable, mesh, axis: str = "dp", lr: float = 1e-2):
    """SGD DDP step: batch sharded over `axis`, grads averaged with psum.
    Returns step(params, batch...) -> (new_params, loss)."""

    def local_step(params, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, axis), grads)
        loss = jax.lax.pmean(loss, axis)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                            params, grads)
        return new_params, loss

    def step(params, *batch):
        p_spec = jax.tree_util.tree_map(lambda _: P(), params)
        b_spec = tuple(P(axis) for _ in batch)
        fn = shard_map(local_step, mesh=mesh,
                       in_specs=(p_spec,) + b_spec,
                       out_specs=(p_spec, P()),
                       check_rep=False)
        return fn(params, *batch)

    return jax.jit(step)


def zero_shard_params(params, mesh, axis: str = "dp"):
    """Shard every param leaf's dim 0 over `axis` when divisible (ZeRO-3
    placement); indivisible leaves stay replicated."""
    n = mesh.shape[axis]

    def place(p):
        if p.ndim > 0 and p.shape[0] % n == 0:
            return jax.device_put(p, NamedSharding(mesh, P(axis)))
        return jax.device_put(p, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(place, params)


def zero2_step(loss_fn: Callable, mesh, axis: str = "dp", lr: float = 1e-2,
               b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """Adam ZeRO-2: params replicated, optimizer moments sharded over dp.

    reduce_scatter(grads) -> local Adam shard update -> all_gather(params)
    (reference transform_fsdp shard_param=False, compile_dp.py:125-183).
    Leaves whose dim 0 does not divide the axis fall back to replicated
    moments with pmean'd grads.
    Returns (step, init_opt): step((params, opt, count), batch...) ->
    ((new_params, new_opt, count), loss).
    """
    n = mesh.shape[axis]

    def shardable(p):
        return p.ndim > 0 and p.shape[0] % n == 0

    def init_opt(params):
        def moment(p):
            if shardable(p):
                shard_shape = (p.shape[0] // n,) + p.shape[1:]
                z = jnp.zeros((n,) + shard_shape, p.dtype)
                return jax.device_put(z, NamedSharding(mesh, P(axis)))
            return jnp.zeros_like(p)

        return {"mu": jax.tree_util.tree_map(moment, params),
                "nu": jax.tree_util.tree_map(moment, params)}

    def local_step(params, mu, nu, count, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        loss = jax.lax.pmean(loss, axis)
        count = count + 1
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)

        def update(p, g, m, v):
            if shardable(p):
                # grads: [d0, ...] -> reduce_scatter -> [d0/n, ...]
                g_shard = jax.lax.psum_scatter(g, axis, scatter_dimension=0,
                                               tiled=True) / n
                m, v = m[0], v[0]
                p_shard = jax.lax.dynamic_slice_in_dim(
                    p, jax.lax.axis_index(axis) * g_shard.shape[0],
                    g_shard.shape[0], axis=0)
                m = b1 * m + (1 - b1) * g_shard
                v = b2 * v + (1 - b2) * g_shard * g_shard
                p_new = p_shard - lr * (m / c1) / (jnp.sqrt(v / c2) + eps)
                p_full = jax.lax.all_gather(p_new, axis, axis=0, tiled=True)
                return p_full, m[None], v[None]
            g = jax.lax.pmean(g, axis)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            return p - lr * (m / c1) / (jnp.sqrt(v / c2) + eps), m, v

        flat_p, tdef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        flat_m = jax.tree_util.tree_flatten(mu)[0]
        flat_v = jax.tree_util.tree_flatten(nu)[0]
        new = [update(p, g, m, v) for p, g, m, v in
               zip(flat_p, flat_g, flat_m, flat_v)]
        new_params = jax.tree_util.tree_unflatten(tdef, [t[0] for t in new])
        new_mu = jax.tree_util.tree_unflatten(tdef, [t[1] for t in new])
        new_nu = jax.tree_util.tree_unflatten(tdef, [t[2] for t in new])
        return new_params, new_mu, new_nu, count, loss

    def step(state, *batch):
        params, opt, count = state
        p_spec = jax.tree_util.tree_map(lambda _: P(), params)
        m_spec = jax.tree_util.tree_map(
            lambda p: P(axis) if shardable(p) else P(), params)
        b_spec = tuple(P(axis) for _ in batch)
        fn = shard_map(local_step, mesh=mesh,
                       in_specs=(p_spec, m_spec, m_spec, P()) + b_spec,
                       out_specs=(p_spec, m_spec, m_spec, P(), P()),
                       check_rep=False)
        new_params, mu, nu, count, loss = fn(params, opt["mu"], opt["nu"],
                                             count, *batch)
        return (new_params, {"mu": mu, "nu": nu}, count), loss

    return jax.jit(step), init_opt
