"""Manual data-parallel and ZeRO modes (reference: easydist/torch/compile_dp.py).

The auto solver discovers DP on its own; these wrappers are the explicit
`parallel_mode="ddp"/"zero2"/"zero3"` equivalents (compile_dp.py:55-198),
expressed as sharding annotations + shard_map collectives instead of graph
surgery over NCCL ops:

  ddp    — batch sharded, params replicated, grads pmean'd
  zero2  — + optimizer state sharded over dp: reduce_scatter grads, update
           the local shard, all_gather updated params
  zero3  — fully sharded params AND moments: per-step all_gather of
           params for fw/bw, reduce_scatter grads, shard-local Adam

All gradient reductions route through `easydist_tpu.comm`: with the
default config the wrappers emit the exact historical collectives
(bitwise-identical programs); with `comm_quant_dtype`/`comm_bucket_bytes`
set, gradients travel block-quantized and/or fused into fixed-size
buckets (docs/COMM.md), with sensitive leaves (`comm_quant_skip`) kept at
full precision.

Two opt-in latency-hiding knobs ride on top (docs/COMM.md "Overlapped
flush"):

  * ``edconfig.comm_overlap`` — gradients are flushed in backward
    EMISSION order as a barrier-pinned chain (`comm.overlap`), letting
    XLA slide each collective under the remaining backward compute.
    Values are bitwise-identical to the sequential flush with
    quantization off.
  * ``grad_accum_microbatches=K`` (kwarg or the config default) — the
    batch is split into K microbatches accumulated in a scan; with
    overlap on, microbatch k's backward hides the reduction of
    microbatch k-1's gradients (double buffering).

With both knobs at their defaults the emitted programs are unchanged.

A third opt-in, ``step_guard`` (kwarg, default from
``EASYDIST_STEP_GUARD``), folds the NaN/Inf skip-and-hold guard
(resilience/guard.py) into the jitted step: the carry becomes
``(state, guard_state)`` (seed the second element with
``resilience.init_guard_state()``) and a non-finite step holds the
previous state instead of committing garbage.  Guard OFF takes the
historical code path untouched — the emitted program is bitwise-identical
(tested by jaxpr identity in tests/test_resilience/test_guard.py).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from easydist_tpu import comm
from easydist_tpu import config as edconfig
from easydist_tpu.utils.jax_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P


def _accum_k(grad_accum_microbatches: Optional[int]) -> int:
    """Effective microbatch count: the kwarg wins, else the config knob;
    0/1 both mean no accumulation."""
    k = (edconfig.grad_accum_microbatches if grad_accum_microbatches is None
         else grad_accum_microbatches)
    return int(k) if k else 0


def _maybe_guard(step: Callable, step_guard: Optional[bool]) -> Callable:
    """Fold the NaN/Inf skip-and-hold guard into the (unjitted) step when
    requested; OFF returns `step` itself, so the guard-off trace cannot
    differ from pre-guard builds by construction."""
    on = (edconfig.resilience_step_guard if step_guard is None
          else bool(step_guard))
    if not on:
        return step
    from easydist_tpu.resilience.guard import guard_train_step

    return guard_train_step(step)


def _grad_paths(grads):
    """keystr paths of the grad tree's leaves, flat order (the
    comm_quant_skip opt-out matches against these)."""
    return [jax.tree_util.keystr(kp)
            for kp, _ in jax.tree_util.tree_flatten_with_path(grads)[0]]


def ddp_step(loss_fn: Callable, mesh, axis: str = "dp", lr: float = 1e-2,
             grad_accum_microbatches: Optional[int] = None,
             step_guard: Optional[bool] = None):
    """SGD DDP step: batch sharded over `axis`, grads averaged with psum.
    Returns step(params, batch...) -> (new_params, loss); with the guard
    on, step((params, guard_state), batch...) -> ((..., ...), loss)."""
    n = mesh.shape[axis]

    def local_step(params, *batch):
        k = _accum_k(grad_accum_microbatches)
        if k > 1:
            grads, loss = comm.accumulate_gradients(
                loss_fn, params, batch, axis_name=axis, axis_size=n,
                n_micro=k)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            order = (comm.grad_emission_order(loss_fn, params, *batch)
                     if edconfig.comm_overlap else None)
            grads = comm.reduce_gradients(grads, axis, n, op="pmean",
                                          emission_order=order)
        loss = jax.lax.pmean(loss, axis)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                            params, grads)
        return new_params, loss

    def step(params, *batch):
        p_spec = jax.tree_util.tree_map(lambda _: P(), params)
        b_spec = tuple(P(axis) for _ in batch)
        fn = shard_map(local_step, mesh=mesh,
                       in_specs=(p_spec,) + b_spec,
                       out_specs=(p_spec, P()),
                       check_vma=False)
        return fn(params, *batch)

    return jax.jit(_maybe_guard(step, step_guard))


def zero_shard_params(params, mesh, axis: str = "dp"):
    """Shard every param leaf's dim 0 over `axis` when divisible (ZeRO-3
    placement); indivisible leaves stay replicated."""
    n = mesh.shape[axis]

    def place(p):
        if p.ndim > 0 and p.shape[0] % n == 0:
            return jax.device_put(p, NamedSharding(mesh, P(axis)))
        return jax.device_put(p, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(place, params)


def zero3_step(loss_fn: Callable, mesh, axis: str = "dp", lr: float = 1e-2,
               b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
               grad_accum_microbatches: Optional[int] = None,
               step_guard: Optional[bool] = None):
    """Adam ZeRO-3: parameters AND optimizer moments sharded over dp.

    Params live sharded on dim 0; each step all_gathers them for the
    forward/backward (XLA schedules gather/free per layer), reduce_scatters
    grads, and updates only the local shard (reference transform_fsdp
    shard_param=True, compile_dp.py:93-123).  Leaves that do not divide the
    axis stay replicated with pmean'd grads.

    Returns (step, init_state): state = (sharded_params, opt, count);
    step(state, *batch) -> (state, loss).
    """
    n = mesh.shape[axis]

    def shardable(p):
        return p.ndim > 0 and p.shape[0] % n == 0

    def init_state(params):
        def shard(p):
            if shardable(p):
                return jax.device_put(p, NamedSharding(mesh, P(axis)))
            return jax.device_put(p, NamedSharding(mesh, P()))

        sharded = jax.tree_util.tree_map(shard, params)
        def moment(p):
            return jnp.zeros_like(p)

        opt = {"mu": jax.tree_util.tree_map(moment, sharded),
               "nu": jax.tree_util.tree_map(moment, sharded)}
        return (sharded, opt, jnp.zeros((), jnp.int32))

    # local_step needs static knowledge of which leaves are sharded; build
    # it per params structure via a factory
    def make_step(shard_flags, tdef, grad_accum_microbatches=None):
        def local_step(flat_ps, flat_mu, flat_nu, count, *batch):
            full = [jax.lax.all_gather(p, axis, axis=0, tiled=True)
                    if flag else p
                    for p, flag in zip(flat_ps, shard_flags)]
            params = jax.tree_util.tree_unflatten(tdef, full)
            k = _accum_k(grad_accum_microbatches)
            overlap = bool(edconfig.comm_overlap)
            g_paths = _grad_paths(params)

            def reduce_leaf(i, g):
                if shard_flags[i]:
                    return comm.reduce_scatter_grad(g, axis, n,
                                                    path=g_paths[i])
                return comm.all_reduce_grad(g, axis, n, path=g_paths[i])

            if k > 1:
                # the reducer output is shard-shaped for flagged leaves —
                # exactly the local param shards' shapes
                order = (comm.grad_emission_order(loss_fn, params, *batch)
                         if overlap else None)

                def reduce_tree(gtree):
                    fg = jax.tree_util.tree_flatten(gtree)[0]
                    if overlap:
                        fg = comm.chain_leaf_reduces(fg, order, reduce_leaf)
                    else:
                        fg = [reduce_leaf(i, g) for i, g in enumerate(fg)]
                    return jax.tree_util.tree_unflatten(tdef, fg)

                acc_shapes = jax.tree_util.tree_unflatten(tdef, [
                    jax.ShapeDtypeStruct(jnp.shape(p), jnp.result_type(p))
                    for p in flat_ps])
                grads, loss = comm.accumulate_gradients(
                    loss_fn, params, batch, axis_name=axis, axis_size=n,
                    n_micro=k, reduce_tree=reduce_tree,
                    acc_shapes=acc_shapes, overlapped=overlap)
                flat_g = jax.tree_util.tree_flatten(grads)[0]
                pre_reduced = True
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
                flat_g = jax.tree_util.tree_flatten(grads)[0]
                if overlap:
                    # pre-reduce as a backward-ordered pinned chain; the
                    # Adam update below then consumes reduced shards
                    order = comm.grad_emission_order(loss_fn, params,
                                                     *batch)
                    flat_g = comm.chain_leaf_reduces(flat_g, order,
                                                     reduce_leaf)
                    pre_reduced = True
                else:
                    pre_reduced = False
            loss = jax.lax.pmean(loss, axis)
            count = count + 1
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
            new_p, new_m, new_v = [], [], []
            for i, (p_shard, g, m, v, flag) in enumerate(
                    zip(flat_ps, flat_g, flat_mu, flat_nu, shard_flags)):
                if not pre_reduced:
                    g = reduce_leaf(i, g)
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * g * g
                new_p.append(p_shard - lr * (m / c1) / (jnp.sqrt(v / c2) + eps))
                new_m.append(m)
                new_v.append(v)
            return tuple(new_p), tuple(new_m), tuple(new_v), count, loss

        return local_step

    def step(state, *batch):
        params_shards, opt, count = state
        flat_p, tdef = jax.tree_util.tree_flatten(params_shards)
        # a leaf is sharded iff its global dim0 divides the axis; after
        # init_state the leaf still has GLOBAL shape (sharded array), so
        # shardable() applies directly
        shard_flags = tuple(shardable(p) for p in flat_p)
        local = make_step(shard_flags, tdef, grad_accum_microbatches)

        def spec_for(p, flag):
            return P(axis) if flag else P()

        p_specs = [spec_for(p, f) for p, f in zip(flat_p, shard_flags)]
        b_spec = tuple(P(axis) for _ in batch)
        fn = shard_map(
            local, mesh=mesh,
            in_specs=(tuple(p_specs), tuple(p_specs), tuple(p_specs), P())
            + b_spec,
            out_specs=(tuple(p_specs), tuple(p_specs), tuple(p_specs), P(),
                       P()),
            check_vma=False)
        flat_mu = jax.tree_util.tree_flatten(opt["mu"])[0]
        flat_nu = jax.tree_util.tree_flatten(opt["nu"])[0]
        new_p, new_m, new_v, count, loss = fn(tuple(flat_p), tuple(flat_mu),
                                              tuple(flat_nu), count, *batch)
        params = jax.tree_util.tree_unflatten(tdef, list(new_p))
        opt = {"mu": jax.tree_util.tree_unflatten(tdef, list(new_m)),
               "nu": jax.tree_util.tree_unflatten(tdef, list(new_v))}
        return (params, opt, count), loss

    return jax.jit(_maybe_guard(step, step_guard)), init_state


def zero2_step(loss_fn: Callable, mesh, axis: str = "dp", lr: float = 1e-2,
               b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
               grad_accum_microbatches: Optional[int] = None,
               step_guard: Optional[bool] = None):
    """Adam ZeRO-2: params replicated, optimizer moments sharded over dp.

    reduce_scatter(grads) -> local Adam shard update -> all_gather(params)
    (reference transform_fsdp shard_param=False, compile_dp.py:125-183).
    Leaves whose dim 0 does not divide the axis fall back to replicated
    moments with pmean'd grads.
    Returns (step, init_opt): step((params, opt, count), batch...) ->
    ((new_params, new_opt, count), loss).
    """
    n = mesh.shape[axis]

    def shardable(p):
        return p.ndim > 0 and p.shape[0] % n == 0

    def init_opt(params):
        def moment(p):
            if shardable(p):
                shard_shape = (p.shape[0] // n,) + p.shape[1:]
                z = jnp.zeros((n,) + shard_shape, p.dtype)
                return jax.device_put(z, NamedSharding(mesh, P(axis)))
            return jnp.zeros_like(p)

        return {"mu": jax.tree_util.tree_map(moment, params),
                "nu": jax.tree_util.tree_map(moment, params)}

    def local_step(params, mu, nu, count, *batch):
        flat_p, tdef = jax.tree_util.tree_flatten(params)
        g_paths = _grad_paths(params)
        k = _accum_k(grad_accum_microbatches)
        overlap = bool(edconfig.comm_overlap)

        def reduce_leaf(i, g):
            if shardable(flat_p[i]):
                # grads: [d0, ...] -> reduce_scatter -> [d0/n, ...]
                return comm.reduce_scatter_grad(g, axis, n, path=g_paths[i])
            return comm.all_reduce_grad(g, axis, n, path=g_paths[i])

        if k > 1:
            order = (comm.grad_emission_order(loss_fn, params, *batch)
                     if overlap else None)

            def reduce_tree(gtree):
                fg = jax.tree_util.tree_flatten(gtree)[0]
                if overlap:
                    fg = comm.chain_leaf_reduces(fg, order, reduce_leaf)
                else:
                    fg = [reduce_leaf(i, g) for i, g in enumerate(fg)]
                return jax.tree_util.tree_unflatten(tdef, fg)

            acc_shapes = jax.tree_util.tree_unflatten(tdef, [
                jax.ShapeDtypeStruct(
                    (p.shape[0] // n,) + p.shape[1:] if shardable(p)
                    else p.shape, jnp.result_type(p))
                for p in flat_p])
            grads, loss = comm.accumulate_gradients(
                loss_fn, params, batch, axis_name=axis, axis_size=n,
                n_micro=k, reduce_tree=reduce_tree, acc_shapes=acc_shapes,
                overlapped=overlap)
            flat_g = jax.tree_util.tree_flatten(grads)[0]
            pre_reduced = True
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            flat_g = jax.tree_util.tree_flatten(grads)[0]
            if overlap:
                order = comm.grad_emission_order(loss_fn, params, *batch)
                flat_g = comm.chain_leaf_reduces(flat_g, order, reduce_leaf)
                pre_reduced = True
            else:
                pre_reduced = False
        loss = jax.lax.pmean(loss, axis)
        count = count + 1
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)

        def update(i, p, g, m, v):
            if shardable(p):
                g_shard = g if pre_reduced else reduce_leaf(i, g)
                m, v = m[0], v[0]
                p_shard = jax.lax.dynamic_slice_in_dim(
                    p, jax.lax.axis_index(axis) * g_shard.shape[0],
                    g_shard.shape[0], axis=0)
                m = b1 * m + (1 - b1) * g_shard
                v = b2 * v + (1 - b2) * g_shard * g_shard
                p_new = p_shard - lr * (m / c1) / (jnp.sqrt(v / c2) + eps)
                p_full = jax.lax.all_gather(p_new, axis, axis=0, tiled=True)
                return p_full, m[None], v[None]
            g = g if pre_reduced else reduce_leaf(i, g)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            return p - lr * (m / c1) / (jnp.sqrt(v / c2) + eps), m, v

        flat_m = jax.tree_util.tree_flatten(mu)[0]
        flat_v = jax.tree_util.tree_flatten(nu)[0]
        new = [update(i, p, g, m, v) for i, (p, g, m, v) in
               enumerate(zip(flat_p, flat_g, flat_m, flat_v))]
        new_params = jax.tree_util.tree_unflatten(tdef, [t[0] for t in new])
        new_mu = jax.tree_util.tree_unflatten(tdef, [t[1] for t in new])
        new_nu = jax.tree_util.tree_unflatten(tdef, [t[2] for t in new])
        return new_params, new_mu, new_nu, count, loss

    def step(state, *batch):
        params, opt, count = state
        p_spec = jax.tree_util.tree_map(lambda _: P(), params)
        m_spec = jax.tree_util.tree_map(
            lambda p: P(axis) if shardable(p) else P(), params)
        b_spec = tuple(P(axis) for _ in batch)
        fn = shard_map(local_step, mesh=mesh,
                       in_specs=(p_spec, m_spec, m_spec, P()) + b_spec,
                       out_specs=(p_spec, m_spec, m_spec, P(), P()),
                       check_vma=False)
        new_params, mu, nu, count, loss = fn(params, opt["mu"], opt["nu"],
                                             count, *batch)
        return (new_params, {"mu": mu, "nu": nu}, count), loss

    return jax.jit(_maybe_guard(step, step_guard)), init_opt
