"""Utility layer (reference: easydist/utils/)."""

from .timer import EDTimer  # noqa: F401
from .testing import cpu_mesh  # noqa: F401
