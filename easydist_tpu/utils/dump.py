"""Debug dumps: graphviz DOT of the MetaIR graph with solved placements,
and optimized-HLO text of compiled executables.

Reference analog: fx graph pdf/graphviz dumps (`DUMP_FX_GRAPH`,
torch/compile_auto.py:487-508) and per-pp-submodule `save_graphviz_dot`
(torch/experimental/pp/utils.py).  On TPU the two artifacts you reach for
when a 100-layer plan goes sideways are the placement-annotated dataflow
graph (which op chose which sharding, where the reshards happen) and XLA's
optimized HLO (what GSPMD actually emitted) — both land in
`edconfig.dump_dir`.
"""

from __future__ import annotations

from typing import Dict, List, Sequence


def _fmt_strategy(per_axis: Sequence[Dict], axis_names, name: str) -> str:
    parts = []
    for ax, chosen in zip(axis_names, per_axis):
        s = chosen.get(name)
        if s is None:
            continue
        outs = ",".join(repr(p) for p in s.out_placements)
        parts.append(f"{ax}:{outs}")
    return " ".join(parts)


def _resharded_edges(graph, per_axis) -> set:
    """(producer_name, consumer_name) pairs whose placements differ on any
    axis — where a collective/reshape lands in the emitted program."""
    hot = set()
    for chosen in per_axis:
        for node in graph.ops:
            s = chosen.get(node.name)
            if s is None:
                continue
            for idx, v in enumerate(node.invars):
                if v is None or v.producer is None:
                    continue
                up = chosen.get(v.producer.name)
                if up is None:
                    continue
                p_up = up.out_placements[v.producer_idx] \
                    if v.producer_idx < len(up.out_placements) else None
                p_dn = s.in_placements[idx] \
                    if idx < len(s.in_placements) else None
                rep_up = p_up is None or p_up.is_replicate()
                rep_dn = p_dn is None or p_dn.is_replicate()
                if rep_up != rep_dn or (not rep_up and p_up != p_dn):
                    hot.add((v.producer.name, node.name))
    return hot


def metagraph_to_dot(graph, per_axis: Sequence[Dict], axis_names) -> str:
    """Graphviz DOT of the dataflow graph: one box per op annotated with
    its op_key and chosen out-placements per axis; edges that reshard
    (producer/consumer placement mismatch) are red and bold."""
    lines: List[str] = [
        "digraph metair {",
        "  rankdir=TB;",
        '  node [shape=box, fontname="monospace", fontsize=9];',
    ]
    hot = _resharded_edges(graph, per_axis)
    for node in list(graph.inputs) + list(graph.ops):
        strat = _fmt_strategy(per_axis, axis_names, node.name)
        shape = ""
        if node.outvars and node.outvars[0] is not None:
            shape = "x".join(str(d) for d in node.outvars[0].shape)
        label = f"{node.name}\\n{node.op_key} [{shape}]"
        if strat:
            label += f"\\n{strat}"
        color = ' style=filled fillcolor="lightyellow"' if node.is_input \
            else ""
        lines.append(f'  "{node.name}" [label="{label}"{color}];')
    for node in graph.ops:
        for v in node.invars:
            if v is None or v.producer is None:
                continue
            attr = ' [color=red, penwidth=2.0]' \
                if (v.producer.name, node.name) in hot else ""
            lines.append(f'  "{v.producer.name}" -> "{node.name}"{attr};')
    lines.append("}")
    return "\n".join(lines)


def dump_hlo(executable, path: str) -> None:
    """Write an executable's optimized HLO (post-GSPMD: real collectives,
    fusions, layouts) to `path`."""
    try:
        text = executable.as_text()
    except Exception:
        return
    with open(path, "w") as f:
        f.write(text)
