"""Testing helpers: virtual multi-device CPU meshes in one process — the TPU
analog of the reference's mock device meshes (utils/testing/mock.py:16-50)
and its multi-process `spawn` harness (spawn.py): XLA's
`--xla_force_host_platform_device_count` gives N-device semantics with no
hardware and no process fleet."""

from __future__ import annotations

import os


def force_cpu_devices(n: int = 8) -> None:
    """Must run before jax initializes a backend (e.g. top of conftest)."""
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        f" --xla_force_host_platform_device_count={n}"
    import jax

    jax.config.update("jax_platforms", "cpu")


def cpu_mesh(shape, axis_names, dcn_axes=()):
    """Build a CPU mesh for tests; requires force_cpu_devices() earlier."""
    import jax

    from easydist_tpu.jaxfront.mesh import make_device_mesh

    n = 1
    for s in shape:
        n *= s
    return make_device_mesh(shape, axis_names, devices=jax.devices()[:n],
                            dcn_axes=dcn_axes)
