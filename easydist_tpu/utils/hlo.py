"""Optimized-HLO introspection: collective op census.

The single-chip benchmark cannot see plan quality (solving is skipped on a
1-device mesh), so the quality gate compares the collectives the compiled
program actually contains against a hand-written GSPMD sharding of the same
step (reference measurement discipline: benchmark/torch/bench_torch.py:50-100).
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")


def collective_summary(hlo_text: str) -> Dict[str, Tuple[int, int]]:
    """{collective op name: (count, result bytes)} for an optimized HLO dump.

    Counts each op once (async -start/-done pairs count as one, on the
    -start line) and sums the result tuple's element bytes.
    """
    out: Dict[str, Tuple[int, int]] = {}
    pat = re.compile(r"\s(" + "|".join(_COLLECTIVES) + r")(-start)?\(")
    for line in hlo_text.splitlines():
        m = pat.search(line)
        if m is None:
            continue
        op = m.group(1)
        rhs = line.split("=", 1)
        seg = ""
        if len(rhs) > 1 and op in rhs[1]:
            seg = rhs[1][:rhs[1].index(op)]
        shapes = re.findall(r"(\w+)\[([\d,]*)\]", seg)
        if m.group(2) and len(shapes) >= 2:
            # async -start result tuples alias (operands..., results...) —
            # a combined collective carries several tensors; count the
            # result half once, like the sync form
            shapes = shapes[len(shapes) // 2:]
        total = 0
        for dt, shape in shapes:
            n = 1
            for d in shape.split(","):
                if d:
                    n *= int(d)
            total += n * _DTYPE_BYTES.get(dt, 4)
        cnt, byts = out.get(op, (0, 0))
        out[op] = (cnt + 1, byts + total)
    return out


def total_collective_bytes(summary: Dict[str, Tuple[int, int]]) -> int:
    return sum(b for _, b in summary.values())


def total_collective_count(summary: Dict[str, Tuple[int, int]]) -> int:
    return sum(c for c, _ in summary.values())
