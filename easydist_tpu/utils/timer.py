"""Benchmark timer (reference: easydist/utils/timer.py:24-56 — cuda-event
timing there; `block_until_ready` fencing here)."""

from __future__ import annotations

import time
from typing import Callable

import jax


class EDTimer:

    def __init__(self, func: Callable, trials: int = 10, warmup_trials: int = 3):
        self.func = func
        self.trials = trials
        self.warmup_trials = warmup_trials

    def time(self) -> float:
        """Mean seconds per call, device-fenced."""
        out = None
        for _ in range(self.warmup_trials):
            out = self.func()
        jax.block_until_ready(out)
        start = time.perf_counter()
        for _ in range(self.trials):
            out = self.func()
        jax.block_until_ready(out)
        return (time.perf_counter() - start) / self.trials
