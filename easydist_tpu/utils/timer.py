"""Benchmark timing (reference: easydist/utils/timer.py:24-56 — cuda-event
timing there).

One timing discipline for every measurement in the package:
`jax.block_until_ready` does NOT block through the axon TPU tunnel (a
chained-matmul probe once "measured" 41,180 TFLOP/s, ~200x v5e bf16 peak —
the round-1 benchmark anomaly), so completion is forced by reading ONE
scalar back to the host, and every measurement is two-point —
time(n2 calls) - time(n1 calls) over (n2 - n1) — which cancels the fixed
dispatch + roundtrip overhead.  bench.py documents the same recipe for its
state-threading variant.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def host_sync(out) -> None:
    """Force completion of `out`'s dependency chain via a scalar host
    readback (immune to the tunnel's no-op block_until_ready)."""
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if hasattr(l, "dtype")]
    if leaves:
        np.asarray(jnp.sum(leaves[-1]).astype(jnp.float32))


def two_point_time(fn: Callable, args=(), n1: int = 3, n2: int = 12,
                   retries: int = 2) -> float:
    """Seconds per call of `fn(*args)`, free of fixed dispatch/roundtrip
    overhead.  Retries an inverted sample (t2 <= t1, a tunnel hiccup)
    rather than fabricating impossible throughput; degenerate timing falls
    back to the bounded t2/n2."""
    def run(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        host_sync(out)
        return time.perf_counter() - t0

    run(2)  # warm (compile caches, allocator)
    t1 = t2 = 0.0
    for _ in range(retries):
        t1, t2 = run(n1), run(n2)
        if t2 > t1:
            return (t2 - t1) / (n2 - n1)
    return max(t2, 1e-9) / n2


class EDTimer:

    def __init__(self, func: Callable, trials: int = 12,
                 warmup_trials: int = 3):
        self.func = func
        self.trials = trials
        self.warmup_trials = warmup_trials

    def time(self) -> float:
        """Seconds per call (two-point host-readback; see module doc)."""
        return two_point_time(self.func, n1=max(2, self.trials // 4),
                              n2=self.trials)
