"""Version-portability shims for the jax API surface this codebase uses.

The code targets the modern spellings (`jax.shard_map` with `check_vma=`,
pallas `CompilerParams`); older jaxlibs (<= 0.4.x, still common in
containers) only ship the experimental spellings (`jax.experimental.
shard_map.shard_map` with `check_rep=`, `TPUCompilerParams`).  Every call
site imports from here and keeps writing the modern form.
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    @functools.wraps(_shard_map)
    def shard_map(f, *args, **kwargs):
        # pre-rename jax: the replication-check knob is `check_rep`
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            # modern `axis_names` lists the MANUAL axes; the old API takes
            # the complement as `auto` (axes left to GSPMD)
            manual = frozenset(kwargs.pop("axis_names"))
            mesh = kwargs.get("mesh", args[0] if args else None)
            kwargs["auto"] = frozenset(mesh.axis_names) - manual
        return _shard_map(f, *args, **kwargs)


def tpu_compiler_params(pltpu_module, **kwargs):
    """pltpu.CompilerParams(**kwargs), falling back to the pre-rename
    TPUCompilerParams class on older pallas."""
    cls = getattr(pltpu_module, "CompilerParams", None) \
        or getattr(pltpu_module, "TPUCompilerParams")
    return cls(**kwargs)
