"""Calibrated performance simulator, capacity planner, and SLO
autoscaler (docs/SIMULATOR.md).

Three connected layers over one cost vocabulary:

  `sim.simulate`   discrete-event replay of a solved MetaIR graph — op
                   times from the PerfDB op-profile/calibrate datasheet,
                   collective times and overlap discounts from
                   `autoflow.cost_model`, pipeline schedules replayed
                   from the 1F1B tick tables.  Predicts train step time,
                   decode tokens/s at a given occupancy, and TTFT under
                   chunked prefill, validated against `bench.py` actuals
                   within `SIM_REL_ERROR_BOUND`.
  `sim.capacity`   MeshDesc + TrafficSpec + SLO -> ranked replica plans
                   through the simulator plus an open-loop queueing
                   layer over the router's least-loaded dispatch.
  `sim.autoscale`  the control loop: ServeMetrics occupancy/p99 via
                   PerfDB snapshots, planner target with hysteresis,
                   FleetRouter drain / replica spin-up actuation.

Layer-9 analyze rules audit the whole stack: SIM001 (prediction drift
beyond the committed bound) and SIM002 (autoscale flap/oscillation).
"""

from .autoscale import Autoscaler, AutoscaleConfig, MetricsView
from .capacity import (SLO, CapacityPlan, CapacityPlanner, ReplicaProfile,
                       TrafficSpec)
from .events import Event, EventLog, ServerPool, Stream, percentile
from .simulate import (RESIDUAL_KEY, SIM_REL_ERROR_BOUND, OpTimeTable,
                       SimReport, load_residual, predict_decode_throughput,
                       predict_fn_seconds, predict_pipeline_step,
                       predict_ttft, relative_error, replay_graph,
                       simulate_pipeline, simulate_train_step,
                       store_residual)

__all__ = [
    "Autoscaler", "AutoscaleConfig", "MetricsView",
    "SLO", "CapacityPlan", "CapacityPlanner", "ReplicaProfile",
    "TrafficSpec",
    "Event", "EventLog", "ServerPool", "Stream", "percentile",
    "RESIDUAL_KEY", "SIM_REL_ERROR_BOUND", "OpTimeTable", "SimReport",
    "load_residual", "predict_decode_throughput", "predict_fn_seconds",
    "predict_pipeline_step", "predict_ttft", "relative_error",
    "replay_graph", "simulate_pipeline", "simulate_train_step",
    "store_residual",
]
