"""Capacity planner: "how many chips for X req/s at p99 < Y?"

Couples the calibrated simulator (`sim.simulate` — per-replica service
times) to an open-loop queueing replay (`sim.events.ServerPool` — FCFS,
least-loaded dispatch, the fleet router's occupancy scoring term) over a
deterministic sampled traffic trace, and sweeps replica counts and
prefill/decode splits over a `MeshDesc` (reshard/plan.py).  AoiZora
(arXiv:2606.17566) does exactly this placement-over-mesh-description
reasoning for inference capacity; DistIR supplies the calibrated service
times underneath.

Everything is host-side python over descriptions — a full sweep of a
16-replica mesh runs in milliseconds, which is what lets the autoscaler
re-plan on every control tick.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .events import EventLog, ServerPool, percentile

__all__ = ["TrafficSpec", "SLO", "ReplicaProfile", "CapacityPlan",
           "CapacityPlanner"]


@dataclass(frozen=True)
class TrafficSpec:
    """Open-loop arrival spec: Poisson arrivals at `req_per_s`, request
    shapes drawn from the (choice, weight) distributions, and
    `prefix_reuse` of requests hitting a warm prefix cache (their leading
    chunks are free — the serving layer's prefix-trie contract)."""

    req_per_s: float
    prompt_lens: Tuple[int, ...] = (64,)
    prompt_weights: Tuple[float, ...] = ()
    output_lens: Tuple[int, ...] = (16,)
    output_weights: Tuple[float, ...] = ()
    prefix_reuse: float = 0.0

    @classmethod
    def from_metrics(cls, snapshot: Dict[str, Any],
                     elapsed_s: float) -> "TrafficSpec":
        """Estimate a TrafficSpec from a live replica's
        `ServeMetrics.snapshot()` over an `elapsed_s` observation window —
        closing the loop from admission counters back into the planner
        (ROADMAP: feed `sim.capacity` from serving telemetry instead of
        hand-written specs).

          * arrival rate: admissions (`prefills`) / elapsed_s;
          * prompt distribution: the exact per-length admission histogram
            (`prompt_hist`), lengths as choices, counts as weights;
          * output length: mean tokens generated per completed request
            (one choice — the planner's queueing replay only needs the
            service-time mass, not the tail shape);
          * prefix_reuse: restored-token fraction
            (`prefix_tokens_reused / prefix_tokens_total`), the same
            quantity the prefix_cache_hit_rate gauge tracks.
        """
        if elapsed_s <= 0.0:
            raise ValueError(f"elapsed_s must be positive, got {elapsed_s}")
        counters = snapshot.get("counters", {})
        prefills = int(counters.get("prefills", 0))
        if prefills < 1:
            raise ValueError("snapshot has no admissions to estimate from")
        hist = {int(k): int(v)
                for k, v in (snapshot.get("prompt_hist") or {}).items()}
        if not hist:
            raise ValueError("snapshot carries no prompt_hist (admissions "
                             "predate the histogram, or a non-serving "
                             "snapshot was passed)")
        lens = tuple(sorted(hist))
        weights = tuple(float(hist[l]) for l in lens)
        completed = int(counters.get("requests_completed", 0)) or prefills
        generated = int(counters.get("tokens_generated", 0))
        out_mean = max(1, round(generated / completed)) if generated else 16
        total = int(counters.get("prefix_tokens_total", 0))
        reused = int(counters.get("prefix_tokens_reused", 0))
        return cls(req_per_s=prefills / elapsed_s,
                   prompt_lens=lens, prompt_weights=weights,
                   output_lens=(int(out_mean),),
                   prefix_reuse=(reused / total) if total else 0.0)

    def sample(self, n: int, seed: int = 0
               ) -> List[Tuple[float, int, int, bool]]:
        """Deterministic trace of `n` arrivals:
        [(arrival_s, prompt_len, output_len, prefix_hit)]."""
        import numpy as np

        rng = np.random.default_rng(seed)
        if self.req_per_s <= 0.0:
            raise ValueError("req_per_s must be positive")
        gaps = rng.exponential(1.0 / self.req_per_s, size=n)
        arrivals = np.cumsum(gaps)
        pw = (np.asarray(self.prompt_weights, dtype=float)
              if self.prompt_weights else None)
        ow = (np.asarray(self.output_weights, dtype=float)
              if self.output_weights else None)
        plens = rng.choice(np.asarray(self.prompt_lens), size=n,
                           p=pw / pw.sum() if pw is not None else None)
        olens = rng.choice(np.asarray(self.output_lens), size=n,
                           p=ow / ow.sum() if ow is not None else None)
        hits = rng.random(n) < self.prefix_reuse
        return [(float(arrivals[i]), int(plens[i]), int(olens[i]),
                 bool(hits[i])) for i in range(n)]


@dataclass(frozen=True)
class SLO:
    """The serving objective the plan must meet."""

    ttft_p99_s: float
    per_token_p99_s: float


@dataclass(frozen=True)
class ReplicaProfile:
    """Simulator-derived service model of ONE replica: what
    `sim.simulate` predicts for its decode step and prefill chunk."""

    per_token_s: float       # one batched decode round (all live slots)
    chunk_s: float           # one chunked-prefill step
    chunk_tokens: int        # prompt tokens absorbed per chunk
    n_slots: int             # decode slots per replica
    chips: int = 1           # devices one replica occupies

    def prefill_chunks(self, prompt_len: int, prefix_hit: bool) -> int:
        chunks = max(1, math.ceil(prompt_len / max(1, self.chunk_tokens)))
        if prefix_hit:
            # a warm prefix covers all but the trailing chunk (the trie
            # caches whole pages; the tail always recomputes)
            chunks = 1
        return chunks

    def ttft_service_s(self, prompt_len: int, prefix_hit: bool) -> float:
        from .simulate import predict_ttft

        n = self.prefill_chunks(prompt_len, prefix_hit)
        return predict_ttft(self.chunk_s, n, self.per_token_s)

    def decode_service_s(self, output_len: int) -> float:
        return max(0, output_len - 1) * self.per_token_s


@dataclass
class CapacityPlan:
    """One evaluated point of the sweep, rankable."""

    n_replicas: int
    n_prefill: int            # 0 = colocated prefill+decode
    chips: int
    feasible: bool
    ttft_p99_s: float
    per_token_p99_s: float
    utilization: float        # busy fraction of the decode slots
    headroom: float           # 1 - max(slo fractions); higher = safer
    detail: Dict[str, Any] = field(default_factory=dict)

    def sort_key(self) -> Tuple:
        # feasible plans first, then fewest chips, then most headroom
        return (not self.feasible, self.chips, -self.headroom)

    def as_dict(self) -> Dict[str, Any]:
        return {"n_replicas": self.n_replicas,
                "n_prefill": self.n_prefill, "chips": self.chips,
                "feasible": self.feasible,
                "ttft_p99_s": round(self.ttft_p99_s, 6),
                "per_token_p99_s": round(self.per_token_p99_s, 9),
                "utilization": round(self.utilization, 4),
                "headroom": round(self.headroom, 4)}


class CapacityPlanner:

    def __init__(self, profile: ReplicaProfile, mesh_desc,
                 n_requests: int = 512, seed: int = 0):
        self.profile = profile
        self.mesh = mesh_desc
        self.n_requests = int(n_requests)
        self.seed = int(seed)
        if profile.chips < 1:
            raise ValueError("chips per replica must be >= 1")
        self.max_replicas = max(1, self.mesh.n_devices // profile.chips)

    # ------------------------------------------------------------ evaluate

    def evaluate(self, n_replicas: int, traffic: TrafficSpec, slo: SLO,
                 n_prefill: int = 0) -> CapacityPlan:
        """Open-loop queueing replay of one configuration.

        Requests flow prefill -> decode.  With `n_prefill` > 0 the pools
        are disaggregated (the router's prefill/decode split); with 0,
        prefill steals the shared replica, which is modeled by folding
        prefill service into the same pool.  Decode capacity is
        slots x replicas (a decode round batches every live slot, so a
        slot is the unit of decode concurrency)."""
        p = self.profile
        n_decode = n_replicas - n_prefill
        if n_decode < 1:
            raise ValueError(
                f"split leaves no decode replicas "
                f"({n_replicas} total, {n_prefill} prefill)")
        trace = traffic.sample(self.n_requests, seed=self.seed)
        log = EventLog()
        prefill_pool = ServerPool(max(1, n_prefill) if n_prefill
                                  else n_decode, log, name="prefill")
        decode_pool = ServerPool(n_decode * p.n_slots, log, name="decode")

        ttfts: List[float] = []
        for arrival, plen, olen, hit in trace:
            svc = p.ttft_service_s(plen, hit)
            _, first_token_t, _ = prefill_pool.submit(arrival, svc)
            ttfts.append(first_token_t - arrival)
            decode_pool.submit(first_token_t, p.decode_service_s(olen))

        horizon = max(decode_pool.drain_time(), prefill_pool.drain_time())
        busy = sum(s - w for s, w in zip(decode_pool.sojourns,
                                         decode_pool.waits))
        util = (busy / (horizon * n_decode * p.n_slots)
                if horizon > 0 else 0.0)
        ttft_p99 = percentile(ttfts, 99.0)
        # a decoding slot commits one token per batched round; queueing
        # for a slot surfaces in TTFT, so steady-state per-token latency
        # is the round time itself
        per_token_p99 = p.per_token_s
        frac_ttft = ttft_p99 / slo.ttft_p99_s if slo.ttft_p99_s > 0 \
            else math.inf
        frac_tok = (per_token_p99 / slo.per_token_p99_s
                    if slo.per_token_p99_s > 0 else math.inf)
        worst = max(frac_ttft, frac_tok)
        return CapacityPlan(
            n_replicas=n_replicas, n_prefill=n_prefill,
            chips=n_replicas * p.chips,
            feasible=worst <= 1.0,
            ttft_p99_s=ttft_p99, per_token_p99_s=per_token_p99,
            utilization=min(1.0, util), headroom=1.0 - worst,
            detail={"ttft_p50_s": percentile(ttfts, 50.0),
                    "n_requests": len(trace)})

    # --------------------------------------------------------------- sweep

    def plan(self, traffic: TrafficSpec, slo: SLO,
             splits: Optional[Sequence[int]] = None) -> List[CapacityPlan]:
        """Sweep replica counts (and prefill/decode splits) across the
        mesh; returns every evaluated plan ranked best-first."""
        plans: List[CapacityPlan] = []
        for n in range(1, self.max_replicas + 1):
            for n_prefill in (splits if splits is not None
                              else range(0, max(1, n // 2) + 1)):
                if n - n_prefill < 1:
                    continue
                plans.append(self.evaluate(n, traffic, slo,
                                           n_prefill=n_prefill))
        plans.sort(key=lambda pl: pl.sort_key())
        return plans

    def min_feasible(self, traffic: TrafficSpec, slo: SLO
                     ) -> Optional[CapacityPlan]:
        """The cheapest plan meeting the SLO, or None when even the full
        mesh cannot (the autoscaler then pins max and warns)."""
        for pl in self.plan(traffic, slo):
            if pl.feasible:
                return pl
        return None

    def target_replicas(self, traffic: TrafficSpec, slo: SLO) -> int:
        """Replica count the autoscaler should converge to: the cheapest
        feasible plan's, or the whole mesh when nothing is feasible."""
        best = self.min_feasible(traffic, slo)
        return best.n_replicas if best is not None else self.max_replicas
