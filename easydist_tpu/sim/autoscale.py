"""SLO autoscaler: a control loop that watches fleet ServeMetrics
through PerfDB snapshots, asks the capacity planner for a target replica
count, and actuates with machinery that already exists — `FleetRouter`
drain for scale-down (hot pages migrate to survivors), replica spin-up
via a caller-supplied factory for scale-up.

Safety properties, in priority order:

  * zero dropped requests: scale-down is a graceful (or evacuate) drain,
    never a kill; the bitwise-parity spine means committed tokens are
    identical to a fixed-fleet run whatever the scaler does;
  * hysteresis: a move needs `confirm_evals` consecutive agreeing
    observations, and the opposite direction is suppressed for
    `cooldown_evals` after any actuation — A-B-A flapping is the SIM002
    analyze finding;
  * graceful degradation: frozen metrics (`autoscale.metrics.stale`) or
    a failing spin-up (`autoscale.scaleup.fail`) hold the current N with
    a loud warning instead of acting on bad data — both are catalogued
    fault points (resilience/faultinject.py) the ramp drill arms.
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from easydist_tpu.resilience.faultinject import fire

logger = logging.getLogger(__name__)

__all__ = ["AutoscaleConfig", "MetricsView", "Autoscaler"]


@dataclass(frozen=True)
class MetricsView:
    """One observation of fleet load, parsed out of a PerfDB snapshot."""

    n_live: int                 # non-draining decode replicas
    occupancy: float            # mean decode-slot occupancy across them
    ttft_p99_s: float
    per_token_p99_s: float
    queue_depth: int
    inflight: int
    marker: tuple               # progress counters; frozen == stale feed
    stale_injected: bool = False


@dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    # threshold policy (used when no planner/traffic hint is wired)
    scale_up_occupancy: float = 0.85
    scale_down_occupancy: float = 0.30
    # hysteresis: consecutive agreeing evals before acting, and evals the
    # OPPOSITE direction stays suppressed after any actuation
    confirm_evals: int = 2
    cooldown_evals: int = 2
    # consecutive frozen-marker observations (with work in flight) before
    # the loop declares its metrics feed stale and degrades to hold
    stale_evals: int = 2
    drain_mode: str = "graceful"
    replica_prefix: str = "as"


class Autoscaler:
    """One instance per fleet.  Call `evaluate()` once per control tick;
    it observes, decides, and (maybe) actuates, appending one entry to
    `decision_log` either way — the SIM002 audit surface."""

    def __init__(self, router, spawn: Callable[[str], Any],
                 config: Optional[AutoscaleConfig] = None,
                 planner=None, slo=None, db=None):
        self.router = router
        self.spawn = spawn
        self.config = config or AutoscaleConfig()
        self.planner = planner
        self.slo = slo
        self.db = db
        self.traffic_hint = None
        self.decision_log: List[Dict[str, Any]] = []
        self.degraded = False
        self._tick = 0
        self._spawned = 0
        self._pending_dir = 0
        self._pending_count = 0
        self._cooldown = 0
        self._cooldown_dir = 0
        self._stale_count = 0
        self._last_view: Optional[MetricsView] = None

    # ------------------------------------------------------------ observe

    def set_traffic_hint(self, traffic) -> None:
        """Feed the planner the current arrival spec (a `TrafficSpec`).
        Open-loop drills know their own rate; production would estimate
        it from the admission counters."""
        self.traffic_hint = traffic

    def observe(self) -> MetricsView:
        """Export the fleet's ServeMetrics into a PerfDB and read them
        back through `snapshot()` — the loop consumes the same metrics
        surface an external dashboard would, not private router state."""
        if fire("autoscale.metrics.stale") and self._last_view is not None:
            # the injected failure mode: the feed keeps serving the LAST
            # exported sample (a wedged exporter), not an error
            view = dataclasses.replace(self._last_view,
                                       stale_injected=True)
            self._last_view = view
            return view

        import os

        from easydist_tpu.runtime.perfdb import PerfDB

        # scratch store unless the caller wired a real one: devnull never
        # unpickles, so the DB starts empty and persist is never called
        db = self.db if self.db is not None else PerfDB(path=os.devnull)
        self.router.export_metrics(db=db, persist=False)
        snap = db.snapshot().get("serving", {})

        # snapshot-only-metrics contract (PROTO004): the autoscaler is
        # an observer — it reads the router's published snapshot, never
        # its private structures
        live = self.router.live_decode_snapshot()
        occs: List[float] = []
        ttfts: List[float] = []
        toks: List[float] = []
        marker: List[int] = []
        for rep in live:
            hist = snap.get(f"engine[{rep['replica_id']}]") or []
            if not hist:
                continue
            last = hist[-1]
            occs.append(float(last.get("gauges", {})
                              .get("decode_slot_occupancy", 0.0)))
            counters = last.get("counters", {})
            marker.append(int(counters.get("tokens_generated", 0)))
            lat = last.get("latency", {})
            ttfts.append(self._hist_p99(lat.get("ttft")))
            toks.append(self._hist_p99(lat.get("per_token")))
        fleet_hist = snap.get("engine[fleet]") or snap.get("fleet") or []
        fleet_gauges = (fleet_hist[-1].get("gauges", {})
                        if fleet_hist else {})
        view = MetricsView(
            n_live=len(live),
            occupancy=sum(occs) / len(occs) if occs else 0.0,
            ttft_p99_s=max(ttfts) if ttfts else 0.0,
            per_token_p99_s=max(toks) if toks else 0.0,
            queue_depth=int(fleet_gauges.get(
                "queue_depth", self.router.total_queue_depth)),
            inflight=int(fleet_gauges.get(
                "router_inflight", self.router.inflight_count)),
            marker=tuple(sorted(marker)))
        self._last_view = view
        return view

    @staticmethod
    def _hist_p99(hist_snap) -> float:
        """p99 out of an exported LatencyHistogram snapshot dict."""
        if not hist_snap:
            return 0.0
        for key in ("p99_s", "p99"):
            if key in hist_snap:
                return float(hist_snap[key])
        return 0.0

    # ------------------------------------------------------------- decide

    def _desired(self, view: MetricsView) -> int:
        cfg = self.config
        if self.planner is not None and self.traffic_hint is not None \
                and self.slo is not None:
            target = self.planner.target_replicas(self.traffic_hint,
                                                  self.slo)
        else:
            target = view.n_live
            busy = view.occupancy >= cfg.scale_up_occupancy \
                or view.queue_depth > 0
            if busy and view.occupancy >= cfg.scale_up_occupancy:
                target = view.n_live + 1
            elif view.occupancy <= cfg.scale_down_occupancy \
                    and view.queue_depth == 0 and view.inflight == 0:
                target = view.n_live - 1
        return max(cfg.min_replicas, min(cfg.max_replicas, target))

    def evaluate(self) -> Dict[str, Any]:
        """One control tick.  Returns (and logs) the decision record."""
        cfg = self.config
        self._tick += 1
        prev_marker = (self._last_view.marker
                       if self._last_view is not None else None)
        view = self.observe()

        entry: Dict[str, Any] = {
            "tick": self._tick, "n_live": view.n_live,
            "occupancy": round(view.occupancy, 4),
            "ttft_p99_s": round(view.ttft_p99_s, 6),
            "queue_depth": view.queue_depth,
        }

        # staleness detector: the progress marker must move while work is
        # in flight; a frozen feed means every number below is fiction
        if prev_marker is not None and view.marker == prev_marker \
                and (view.queue_depth > 0 or view.inflight > 0):
            self._stale_count += 1
        else:
            self._stale_count = 0
            if self.degraded:
                logger.info("[autoscale] metrics feed recovered")
            self.degraded = False
        if self._stale_count >= cfg.stale_evals:
            self.degraded = True
            logger.warning(
                "[autoscale] metrics feed is STALE (%d frozen "
                "observations with work in flight) — holding %d "
                "replicas, refusing to act on dead numbers",
                self._stale_count, view.n_live)
            entry.update(action="hold", target=view.n_live,
                         reason="metrics_stale", degraded=True)
            self.decision_log.append(entry)
            return entry

        target = self._desired(view)
        entry["target"] = target
        direction = (1 if target > view.n_live
                     else -1 if target < view.n_live else 0)

        if direction == 0:
            # idempotence: target == current never actuates and clears
            # any half-confirmed move
            self._pending_dir = 0
            self._pending_count = 0
            entry.update(action="hold", reason="at_target")
        elif self._cooldown > 0 and direction == -self._cooldown_dir:
            self._cooldown -= 1
            entry.update(action="hold", reason="cooldown_suppressed")
        else:
            if self._cooldown > 0:
                self._cooldown -= 1
            if direction == self._pending_dir:
                self._pending_count += 1
            else:
                self._pending_dir = direction
                self._pending_count = 1
            if self._pending_count < cfg.confirm_evals:
                entry.update(action="hold", reason="hysteresis_pending",
                             pending=self._pending_count)
            else:
                self._pending_dir = 0
                self._pending_count = 0
                if direction > 0:
                    added = self._scale_up(target - view.n_live)
                    entry.update(action="scale_up" if added else "hold",
                                 added=added,
                                 reason="planner_target" if added
                                 else "scaleup_failed")
                else:
                    drained = self._scale_down(view.n_live - target)
                    entry.update(action="scale_down" if drained
                                 else "hold", drained=drained,
                                 reason="planner_target" if drained
                                 else "no_drain_candidate")
                if entry["action"] != "hold":
                    self._cooldown = cfg.cooldown_evals
                    self._cooldown_dir = direction
        self.decision_log.append(entry)
        return entry

    # ------------------------------------------------------------ actuate

    def _scale_up(self, n: int) -> List[str]:
        """Spin up `n` replicas via the factory.  A spin-up failure
        mid-ramp (`autoscale.scaleup.fail`) keeps what already joined,
        warns, and holds — the fleet stays consistent."""
        added: List[str] = []
        for _ in range(n):
            self._spawned += 1
            rid = f"{self.config.replica_prefix}{self._spawned}"
            try:
                if fire("autoscale.scaleup.fail"):
                    raise RuntimeError(
                        f"injected spin-up failure for {rid!r}")
                session = self.spawn(rid)
                self.router.add_replica(session, role="decode")
            except Exception as e:
                self.degraded = True
                logger.warning(
                    "[autoscale] replica spin-up FAILED mid-ramp (%s) — "
                    "holding at current fleet size; in-flight work is "
                    "unaffected", e)
                break
            added.append(rid)
        return added

    def _scale_down(self, n: int) -> List[str]:
        """Drain the `n` least-loaded eligible decode replicas.  Draining
        is zero-drop by construction: the router keeps stepping the
        leaving replica until its in-flight work retires, then migrates
        its hot pages to survivors."""
        live = self.router.live_decode_snapshot(eligible_only=True)
        keep = self.config.min_replicas
        n = min(n, max(0, len(live) - keep))
        live.sort(key=lambda r: (r["queue_depth"], r["hot_pools"],
                                 r["replica_id"]))
        drained: List[str] = []
        for rep in live[:n]:
            rid = rep["replica_id"]
            try:
                self.router.drain(rid, mode=self.config.drain_mode)
            except Exception as e:
                # the target went ineligible/away mid-decision: skip it,
                # the next tick re-plans against the new fleet
                logger.warning("[autoscale] drain of %s failed (%s); "
                               "re-planning next tick", rid, e)
                continue
            drained.append(rid)
        return drained

    # ------------------------------------------------------------ summary

    def stats(self) -> Dict[str, Any]:
        actions = [d for d in self.decision_log
                   if d.get("action") in ("scale_up", "scale_down")]
        return {"ticks": self._tick,
                "actions": len(actions),
                "scale_ups": sum(1 for d in actions
                                 if d["action"] == "scale_up"),
                "scale_downs": sum(1 for d in actions
                                   if d["action"] == "scale_down"),
                "degraded": self.degraded,
                "decision_log": list(self.decision_log)}
