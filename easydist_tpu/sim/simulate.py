"""Calibrated performance simulator: discrete-event replay of a solved
MetaIR graph (DistIR, arXiv:2111.05426 — trace-driven prediction over a
distributed IR with per-op measured costs).

Cost sources, in priority order, all consumed through read-only PerfDB
snapshots (`runtime/perfdb.py::snapshot`):

  1. measured per-op seconds from the op-profile DB
     (`runtime/op_profile.py::profile_ops`), keyed by the SAME signature
     string the MetaIR bridge stamps on each node;
  2. the node's `compute_proxy` / exact-flops roofline against the
     calibrated `hbm_bandwidth`/`peak_flops`
     (`runtime/calibrate.py::calibrate` / the device datasheet);
  3. the solver's conservative output-bytes/HBM proxy.

Collective seconds come from the SAME alpha-beta closed forms the solver
prices edges with (`autoflow/cost_model.py::resharding_cost`), and the
overlap discount is the solver's `overlap_discount_ratio()` — simulator
and solver never disagree about what a collective costs, which is the
DistIR deterministic-pricing principle this repo already applies to
elastic resharding.

Because summed per-op times systematically miss what fusion and dispatch
do to a whole program, predictions go through a one-point multiplicative
RESIDUAL per domain ("train" / "decode" / "prefill"), calibrated on one
preset and validated on the others (`bench.py --simulate`).  The
committed validation bound is `SIM_REL_ERROR_BOUND`; drift beyond it is
the SIM001 analyze finding.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from easydist_tpu import config as edconfig

from .events import EventLog, Stream

logger = logging.getLogger(__name__)

__all__ = ["SIM_REL_ERROR_BOUND", "RESIDUAL_KEY", "OpTimeTable",
           "SimReport", "replay_graph", "simulate_train_step",
           "predict_fn_seconds", "simulate_pipeline",
           "predict_pipeline_step", "predict_decode_throughput",
           "predict_ttft", "store_residual", "load_residual",
           "relative_error"]

# committed validation contract: |predicted - measured| / measured on
# every non-calibration preset must stay under this bound (gated by
# bench.py --simulate and scripts/static_checks.sh; see docs/SIMULATOR.md)
SIM_REL_ERROR_BOUND = 0.60

RESIDUAL_KEY = "sim_residual"

_DTYPE_BYTES = {"float32": 4, "f32": 4, "float64": 8, "f64": 8,
                "bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
                "int64": 8, "int32": 4, "int16": 2, "int8": 1,
                "uint8": 1, "bool": 1}


def relative_error(predicted: float, measured: float) -> float:
    if measured <= 0.0:
        return math.inf if predicted > 0.0 else 0.0
    return abs(predicted - measured) / measured


# --------------------------------------------------------------- op table

class OpTimeTable:
    """Per-op seconds resolver over one PerfDB snapshot.

    `node_seconds` mirrors the solver's compute pricing exactly
    (autoflow/solver.py cost prep): measured signature time first, then
    the flops/bytes roofline, then the output-bytes/HBM proxy — so the
    simulator predicts with the same numbers the solver optimized
    against."""

    def __init__(self, op_times: Dict[str, float],
                 hbm_bandwidth: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        self.op_times = dict(op_times)
        self.hbm_bandwidth = hbm_bandwidth or edconfig.hbm_bandwidth
        self.peak_flops = peak_flops or edconfig.peak_flops
        self.hits = 0
        self.misses = 0

    @classmethod
    def from_perfdb(cls, db=None) -> "OpTimeTable":
        """Build from the PerfDB snapshot: this backend's op-profile table
        plus any stored calibrate() fit (measured hbm_bandwidth wins over
        the datasheet/config default)."""
        from easydist_tpu.runtime.calibrate import _CAL_KEY, _backend_key
        from easydist_tpu.runtime.op_profile import backend_key
        from easydist_tpu.runtime.perfdb import PerfDB

        snap = (db or PerfDB()).snapshot()
        op_times = dict(snap.get(backend_key(), {}))
        cal = snap.get(_CAL_KEY, {}).get(_backend_key()) or {}
        return cls(op_times,
                   hbm_bandwidth=cal.get("hbm_bandwidth"),
                   peak_flops=cal.get("peak_flops"))

    def node_seconds(self, sig: Optional[str], out_bytes: float,
                     flops: Optional[float] = None,
                     compute_proxy: Optional[float] = None,
                     in_bytes: float = 0.0) -> float:
        measured = self.op_times.get(sig) if sig else None
        if measured is not None:
            self.hits += 1
            return float(measured)
        self.misses += 1
        if compute_proxy is not None:
            return float(compute_proxy)
        if flops:
            return max(flops / self.peak_flops,
                       (in_bytes + out_bytes) / self.hbm_bandwidth)
        return out_bytes / self.hbm_bandwidth

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


# ----------------------------------------------------------------- report

@dataclass
class SimReport:
    """One prediction with its replay breakdown."""

    predicted_s: float
    compute_s: float = 0.0
    comm_s: float = 0.0          # total seconds on the wire
    comm_exposed_s: float = 0.0  # wire seconds NOT hidden under compute
    n_ops: int = 0
    n_collectives: int = 0
    op_db_hit_rate: float = 0.0
    residual: float = 1.0
    detail: Dict[str, Any] = field(default_factory=dict)
    log: Optional[EventLog] = None

    def scaled(self, residual: float) -> "SimReport":
        """Apply a calibrated domain residual to the headline number."""
        out = SimReport(self.predicted_s * residual, self.compute_s,
                        self.comm_s, self.comm_exposed_s, self.n_ops,
                        self.n_collectives, self.op_db_hit_rate,
                        residual, dict(self.detail), self.log)
        return out

    def as_dict(self) -> Dict[str, Any]:
        return {"predicted_s": self.predicted_s,
                "compute_s": round(self.compute_s, 9),
                "comm_s": round(self.comm_s, 9),
                "comm_exposed_s": round(self.comm_exposed_s, 9),
                "n_ops": self.n_ops,
                "n_collectives": self.n_collectives,
                "op_db_hit_rate": round(self.op_db_hit_rate, 3),
                "residual": round(self.residual, 6)}


# ----------------------------------------------------- solved-graph replay

def _placement_or_replicate(p):
    from easydist_tpu.metashard.metair import Placement

    return p if p is not None else Placement.replicate()


def _shards(strat) -> bool:
    return any(p is not None and p.is_shard()
               for p in list(strat.out_placements)
               + list(strat.in_placements))


def replay_graph(graph, strategies: Sequence[Dict[str, Any]],
                 axes: Sequence[Any],
                 op_table: Optional[OpTimeTable] = None) -> SimReport:
    """Discrete-event replay of a solved MetaIR graph.

    `graph` is a `metashard.metair.MetaGraph` in topological order;
    `strategies` is the per-axis `{node_name: NodeStrategy}` list a
    `CompileResult` carries; `axes` the matching `MeshAxisSpec`s.

    Two streams: compute executes ops in topological order; collectives
    occupy the wire, and only `(1 - overlap_discount_ratio())` of each
    collective's seconds block the consumer — the same discount the
    solver applies to reduction edges.  Output vars are handed back
    replicated, so SHARD/PARTIAL producers pay the final collective,
    mirroring the solver's output cost row."""
    from easydist_tpu.autoflow.cost_model import (overlap_discount_ratio,
                                                  resharding_cost)
    from easydist_tpu.metashard.metair import Placement

    table = op_table or OpTimeTable.from_perfdb()
    log = EventLog()
    compute = Stream("compute", log)
    wire = Stream("comm", log)
    ratio = overlap_discount_ratio()
    pairs = [(ax, chosen) for ax, chosen in zip(axes, strategies)
             if chosen and ax.size > 1]

    ready: Dict[str, float] = {}
    visible_end = 0.0
    n_coll = 0
    hits0, miss0 = table.hits, table.misses

    for node in graph.ops:
        out_b = sum(v.size_bytes() for v in node.outvars if v is not None)
        in_b = sum(v.size_bytes() for v in node.invars if v is not None)
        dur = table.node_seconds(node.sig, out_b, node.flops,
                                 node.compute_proxy, in_bytes=in_b)
        intrinsic = 0.0
        for ax, chosen in pairs:
            strat = chosen.get(node.name)
            if strat is None:
                continue
            if strat.compute_cost is not None:
                # composite strategies carry absolute per-strategy seconds
                dur = float(strat.compute_cost)
            elif _shards(strat):
                dur /= ax.size
            intrinsic += getattr(strat, "intrinsic_cost", 0.0)

        t_ready = 0.0
        for idx, var in enumerate(node.invars):
            if var is None:
                continue
            t_in = ready.get(var.name, 0.0)
            comm_s = 0.0
            if var.producer is not None and not var.producer.is_input:
                for ax, chosen in pairs:
                    up_s = chosen.get(var.producer.name)
                    down_s = chosen.get(node.name)
                    if up_s is None or down_s is None:
                        continue
                    up = _placement_or_replicate(
                        up_s.out_placements[var.producer_idx]
                        if var.producer_idx < len(up_s.out_placements)
                        else None)
                    down = _placement_or_replicate(
                        down_s.in_placements[idx]
                        if idx < len(down_s.in_placements) else None)
                    comm_s += resharding_cost(var.size_bytes(), up, down,
                                              ax)
            if comm_s > 0.0:
                n_coll += 1
                c_start, _ = wire.reserve(t_in, comm_s,
                                          label=f"reshard:{var.name}")
                # only the unhidden fraction gates the consumer
                t_in = c_start + (1.0 - ratio) * comm_s
            t_ready = max(t_ready, t_in)

        if intrinsic > 0.0:
            n_coll += 1
            wire.busy_s += intrinsic  # inside the op: always exposed
            dur += intrinsic
        _, end = compute.reserve(t_ready, dur, label=node.name)
        visible_end = max(visible_end, end)
        for v in node.outvars:
            if v is not None:
                ready[v.name] = end

    # graph outputs return replicated: SHARD/PARTIAL producers pay the
    # final collective (all_gather / all_reduce) after their op finishes
    state_outs = set(graph.state_io)
    for var in graph.outputs:
        if var.producer is None or var.name in state_outs:
            continue
        comm_s = 0.0
        for ax, chosen in pairs:
            up_s = chosen.get(var.producer.name)
            if up_s is None:
                continue
            up = _placement_or_replicate(
                up_s.out_placements[var.producer_idx]
                if var.producer_idx < len(up_s.out_placements) else None)
            comm_s += resharding_cost(var.size_bytes(), up,
                                      Placement.replicate(), ax)
        if comm_s > 0.0:
            n_coll += 1
            c_start, _ = wire.reserve(ready.get(var.name, 0.0), comm_s,
                                      label=f"output:{var.name}")
            visible_end = max(visible_end, c_start + comm_s)

    exposed = max(0.0, visible_end - compute.busy_s)
    hit_rate_den = (table.hits - hits0) + (table.misses - miss0)
    return SimReport(
        predicted_s=visible_end,
        compute_s=compute.busy_s,
        comm_s=wire.busy_s,
        comm_exposed_s=min(exposed, wire.busy_s),
        n_ops=len(graph.ops),
        n_collectives=n_coll,
        op_db_hit_rate=((table.hits - hits0) / hit_rate_den
                        if hit_rate_den else 0.0),
        log=log)


def simulate_train_step(compile_result,
                        op_table: Optional[OpTimeTable] = None
                        ) -> SimReport:
    """Replay a `CompileResult` (jaxfront.api) — its solved MetaGraph,
    per-axis strategies, and mesh — into a predicted step time."""
    from easydist_tpu.autoflow.cost_model import MeshAxisSpec

    graph = compile_result.graph
    if graph is None:
        raise ValueError("compile result carries no solved MetaIR graph "
                         "(single-device compile?) — use "
                         "predict_fn_seconds for unsolved programs")
    mesh = compile_result.mesh
    axes = [MeshAxisSpec(str(name), int(size))
            for name, size in zip(mesh.axis_names, mesh.devices.shape)]
    return replay_graph(graph, compile_result.strategies, axes,
                        op_table=op_table)


# --------------------------------------------------- flat-program replay

def predict_fn_seconds(fn, *args,
                       op_table: Optional[OpTimeTable] = None,
                       **kwargs) -> SimReport:
    """Single-device replay of `fn`'s flat jaxpr: every flat eqn priced by
    signature against the op table (the decode-step / prefill-chunk path,
    where there is no solved multi-axis graph to walk)."""
    import jax
    import numpy as np

    from easydist_tpu.jaxfront.inline import inline_calls
    from easydist_tpu.jaxfront.interpreter import eqn_signature

    table = op_table or OpTimeTable.from_perfdb()
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    closed = inline_calls(closed)

    log = EventLog()
    compute = Stream("compute", log)
    hits0, miss0 = table.hits, table.misses
    n_ops = 0
    for eqn in closed.jaxpr.eqns:
        if any(k in eqn.params for k in
               ("jaxpr", "call_jaxpr", "branches", "cond_jaxpr")):
            continue  # flat primitives only, matching profile_ops
        sig = eqn_signature(eqn, None)
        out_b = sum(float(np.prod(v.aval.shape) or 1)
                    * _DTYPE_BYTES.get(str(v.aval.dtype), 4)
                    for v in eqn.outvars)
        compute.reserve(compute.free_at,
                        table.node_seconds(sig, out_b),
                        label=eqn.primitive.name)
        n_ops += 1
    hit_den = (table.hits - hits0) + (table.misses - miss0)
    return SimReport(predicted_s=compute.free_at,
                     compute_s=compute.busy_s, n_ops=n_ops,
                     op_db_hit_rate=((table.hits - hits0) / hit_den
                                     if hit_den else 0.0),
                     log=log)


# ------------------------------------------------------- pipeline replay

def simulate_pipeline(tables: Dict[str, Any], fwd_unit_s: float,
                      bwd_unit_s: float = 0.0) -> SimReport:
    """Replay a 1F1B/interleaved tick table
    (`parallel/pipeline.py::_1f1b_schedule_tables`) under per-unit stage
    costs: every supertick runs in lockstep, so its duration is the
    slowest device's (fwd + bwd) work that tick, and the step is the sum
    over ticks.  The emergent bubble fraction matches
    `schedule_stats(tables)` when stage costs are uniform."""
    f_ok = tables["f_ok"]
    b_ok = tables.get("b_ok")
    U, S = f_ok.shape
    log = EventLog()
    total = 0.0
    busy = 0.0
    for u in range(U):
        tick = 0.0
        for s in range(S):
            work = (fwd_unit_s if f_ok[u, s] else 0.0) + \
                (bwd_unit_s if b_ok is not None and b_ok[u, s] else 0.0)
            busy += work
            tick = max(tick, work)
        total += tick
        if tick > 0.0:
            log.record(total, "supertick", u=u, duration=tick)
    ideal = busy / S if S else 0.0
    report = SimReport(predicted_s=total, compute_s=busy, n_ops=int(U),
                       log=log)
    report.detail["bubble_fraction"] = (
        (total - ideal) / total if total > 0 else 0.0)
    return report


def predict_pipeline_step(pp: int, n_virtual: int, n_micro: int,
                          fwd_unit_s: float, bwd_unit_s: float
                          ) -> SimReport:
    """Convenience: build the 1F1B tick tables and replay them."""
    from easydist_tpu.parallel.pipeline import _1f1b_schedule_tables

    tables = _1f1b_schedule_tables(pp, n_virtual, n_micro)
    return simulate_pipeline(tables, fwd_unit_s, bwd_unit_s)


# --------------------------------------------------- serving predictions

def predict_decode_throughput(per_token_s: float, n_slots: int,
                              occupancy: float = 1.0) -> float:
    """Committed tokens/s of one replica at the given decode-slot
    occupancy: a decode round advances every live slot by one token in
    one (batched) step, so throughput scales with live slots until the
    step itself slows down."""
    if per_token_s <= 0.0:
        return 0.0
    live = max(0.0, min(1.0, occupancy)) * n_slots
    return live / per_token_s


def predict_ttft(chunk_s: float, n_chunks: int, per_token_s: float,
                 queue_wait_s: float = 0.0,
                 prefix_hit_chunks: int = 0) -> float:
    """TTFT under chunked prefill: queueing + the chunks actually
    executed (prefix-cache hits skip leading chunks) + the first decode
    step that commits token one."""
    run_chunks = max(0, n_chunks - prefix_hit_chunks)
    return queue_wait_s + run_chunks * chunk_s + per_token_s


# ----------------------------------------------------- residual handling

def _residual_sub_key(domain: str) -> str:
    import jax

    return f"{jax.default_backend()}:{domain}"


def store_residual(domain: str, scale: float, db=None) -> None:
    """Persist a one-point multiplicative residual (measured/predicted on
    the domain's calibration preset)."""
    from easydist_tpu.runtime.perfdb import PerfDB

    db = db or PerfDB()
    db.record_op_perf(RESIDUAL_KEY, _residual_sub_key(domain),
                      float(scale))
    try:
        db.persist()
    except Exception:
        logger.warning("could not persist sim residual")


def load_residual(domain: str, db=None, default: float = 1.0) -> float:
    from easydist_tpu.runtime.perfdb import PerfDB

    try:
        got = (db or PerfDB()).get_op_perf(RESIDUAL_KEY,
                                           _residual_sub_key(domain))
        return float(got) if got else float(default)
    except Exception:
        return float(default)
