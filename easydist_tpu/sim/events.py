"""Discrete-event substrate for the performance simulator.

Three small pieces, all deterministic and host-only:

  `EventLog`     an append-only trace of (time, kind, payload) records —
                 the replay artifact audits and tests inspect.
  `Stream`       one serially-occupied execution resource (a compute
                 stream, a comm stream, one replica's decode loop).
                 `reserve(ready, dur)` places work at the earliest
                 instant both the work and the stream are ready, exactly
                 like an XLA stream executes enqueued ops in order.
  `ServerPool`   c identical FCFS servers with least-loaded dispatch —
                 the open-loop queueing layer the capacity planner runs
                 arrivals through.  Least-loaded mirrors the fleet
                 router's occupancy scoring term: a new request lands on
                 the replica that frees up first.

DistIR (arXiv:2111.05426) frames distributed-performance prediction as
trace replay over per-op costs on per-device timelines; this module is
that timeline machinery, with the costs supplied by `sim.simulate`.
Nothing here imports jax — events are pure python, so the planner can
sweep hundreds of configurations in milliseconds.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Event", "EventLog", "Stream", "ServerPool", "percentile"]


@dataclass(frozen=True)
class Event:
    time: float
    kind: str
    payload: Dict[str, Any] = field(default_factory=dict)


class EventLog:
    """Append-only, time-ordered-on-read trace of simulation events."""

    def __init__(self):
        self._events: List[Event] = []

    def record(self, time: float, kind: str, **payload) -> Event:
        ev = Event(float(time), kind, dict(payload))
        self._events.append(ev)
        return ev

    def events(self, kind: Optional[str] = None) -> List[Event]:
        evs = [e for e in self._events if kind is None or e.kind == kind]
        return sorted(evs, key=lambda e: (e.time, e.kind))

    def makespan(self) -> float:
        return max((e.time for e in self._events), default=0.0)

    def __len__(self) -> int:
        return len(self._events)


class Stream:
    """One serially-occupied resource: enqueued work runs in order, each
    unit starting when both its inputs and the stream are free."""

    def __init__(self, name: str, log: Optional[EventLog] = None):
        self.name = name
        self.log = log
        self.free_at = 0.0
        self.busy_s = 0.0

    def reserve(self, ready: float, duration: float,
                label: str = "") -> Tuple[float, float]:
        """Place `duration` seconds of work that becomes ready at time
        `ready`; returns (start, end)."""
        if duration < 0.0:
            raise ValueError(f"negative duration {duration} on {self.name}")
        start = max(float(ready), self.free_at)
        end = start + float(duration)
        self.free_at = end
        self.busy_s += float(duration)
        if self.log is not None and duration > 0.0:
            self.log.record(end, f"{self.name}.done", label=label,
                            start=start, duration=float(duration))
        return start, end

    def utilization(self, horizon: Optional[float] = None) -> float:
        h = horizon if horizon is not None else self.free_at
        return self.busy_s / h if h > 0 else 0.0


class ServerPool:
    """`c` identical FCFS servers with least-loaded (earliest-free)
    dispatch.  `submit` returns (start, end, server_idx); sojourn values
    accumulate for percentile queries afterwards."""

    def __init__(self, c: int, log: Optional[EventLog] = None,
                 name: str = "server"):
        if c < 1:
            raise ValueError(f"need at least one server, got {c}")
        self.name = name
        self.log = log
        # (free_at, idx) heap: ties broken by index, so identical traffic
        # on identical pools dispatches identically — determinism is what
        # lets the autoscale drill assert decisions against the planner
        self._free: List[Tuple[float, int]] = [(0.0, i) for i in range(c)]
        heapq.heapify(self._free)
        self.waits: List[float] = []
        self.sojourns: List[float] = []

    @property
    def size(self) -> int:
        return len(self._free)

    def submit(self, arrival: float, service_s: float
               ) -> Tuple[float, float, int]:
        free_at, idx = heapq.heappop(self._free)
        start = max(float(arrival), free_at)
        end = start + float(service_s)
        heapq.heappush(self._free, (end, idx))
        self.waits.append(start - float(arrival))
        self.sojourns.append(end - float(arrival))
        if self.log is not None:
            self.log.record(end, f"{self.name}.served", server=idx,
                            arrival=float(arrival), start=start,
                            service=float(service_s))
        return start, end, idx

    def drain_time(self) -> float:
        return max(t for t, _ in self._free)


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) over a plain list — the
    planner's p99 on simulated sojourns.  Empty input -> 0.0."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1,
                      int(round(q / 100.0 * (len(ordered) - 1)))))
    return float(ordered[rank])
