"""Benchmark case definitions (reference: benchmark/bench_case.py:5-25 —
GPT bs4 seq1024 d12288 h48 L1; wide-ResNet bs128; GAT 4096x12288).

Each case builds (step_fn_or_factory, init_args) at a size scaled for the
available hardware; `run_benchmarks.py` times easydist-compiled vs hand-jit
and emits one JSON line per case."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp


@dataclass
class BenchCase:
    name: str
    make: Callable  # () -> (step, state, batch_args, tokens_per_step)


def _gpt_case(tpu: bool):
    from easydist_tpu.models import GPTConfig, make_gpt_train_step

    cfg = (GPTConfig(vocab=50304, seq=512, dim=768, heads=12, layers=12,
                     dtype="bfloat16") if tpu else GPTConfig.tiny())
    batch = 8

    def make():
        step, init_state = make_gpt_train_step(cfg)
        state = init_state(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.seq),
                                    0, cfg.vocab)
        return step, state, (tokens, tokens), batch * cfg.seq

    return BenchCase("gpt2_train", make)


def _llama_case(tpu: bool):
    from easydist_tpu.models import LlamaConfig, make_llama_train_step

    cfg = (LlamaConfig(vocab=32000, seq=512, dim=1024, heads=16, kv_heads=8,
                       layers=8, ffn_dim=2816, dtype="bfloat16")
           if tpu else LlamaConfig.tiny())
    batch = 4

    def make():
        step, init_state = make_llama_train_step(cfg)
        state = init_state(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.seq),
                                    0, cfg.vocab)
        return step, state, (tokens, tokens), batch * cfg.seq

    return BenchCase("llama_train", make)


def _vit_case(tpu: bool):
    from easydist_tpu.models import ViTConfig, make_vit_train_step

    cfg = ViTConfig.b16(image=224) if tpu else ViTConfig.tiny()
    batch = 32 if tpu else 8

    def make():
        step, init_state = make_vit_train_step(cfg)
        state = init_state(jax.random.PRNGKey(0))
        images = jax.random.normal(jax.random.PRNGKey(1),
                                   (batch, cfg.image, cfg.image, 3))
        labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0,
                                    cfg.classes)
        return step, state, (images, labels), batch

    return BenchCase("vit_train", make)


def _resnet_case(tpu: bool):
    from easydist_tpu.models import make_resnet_train_step, resnet_init

    widths = (64, 128, 256, 512) if tpu else (8, 16)
    batch = 128 if tpu else 8
    image = 64 if tpu else 8

    def make():
        params, arch = resnet_init(jax.random.PRNGKey(0), widths=widths,
                                   blocks_per_stage=2)
        step = make_resnet_train_step(arch)
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, image, image, 3))
        labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 10)
        return step, params, (x, labels), batch

    return BenchCase("resnet_train", make)


def _gat_case(tpu: bool):
    from easydist_tpu.models import GATConfig, gat_init, make_gat_train_step

    cfg = GATConfig.bench(nodes=4096, features=4096, hidden=512) if tpu \
        else GATConfig.tiny()

    def make():
        params = gat_init(cfg, jax.random.PRNGKey(0))
        step = make_gat_train_step(cfg)
        key = jax.random.PRNGKey(1)
        adj = (jax.random.uniform(key, (cfg.nodes, cfg.nodes)) < 0.01)
        adj = jnp.maximum(adj.astype(jnp.float32), jnp.eye(cfg.nodes))
        x = jax.random.normal(jax.random.PRNGKey(2), (cfg.nodes, cfg.features))
        labels = jax.random.randint(jax.random.PRNGKey(3), (cfg.nodes,), 0,
                                    cfg.classes)
        return step, params, (adj, x, labels), cfg.nodes

    return BenchCase("gat_train", make)


def all_cases(tpu: bool):
    return [_gpt_case(tpu), _llama_case(tpu), _vit_case(tpu),
            _resnet_case(tpu), _gat_case(tpu)]
