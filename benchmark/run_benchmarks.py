"""Sweep runner: easydist auto-parallel vs hand-jit per benchmark case
(reference: benchmark/torch/bench_torch.py:50-100 measuring easydist vs
DDP vs FSDP; here the baseline is XLA-native hand-jit).

python benchmark/run_benchmarks.py [--cases gpt2_train,vit_train]
Prints one JSON line per case.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402


def bench_case(case, iters=10):
    from easydist_tpu.jaxfront import easydist_compile, make_device_mesh

    step, state0, batch, tokens_per_step = case.make()
    mesh = make_device_mesh()

    def timed(fn, state):
        out = None
        for _ in range(3):
            out = fn(state, *batch)
            state = out[0]
        jax.block_until_ready(out[1])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(state, *batch)
            state = out[0]
        jax.block_until_ready(out[1])
        return (time.perf_counter() - t0) / iters

    base = jax.jit(step, donate_argnums=(0,))
    compiled = easydist_compile(step)
    ratios, times = [], []
    for _ in range(3):
        t_base = timed(base, case.make()[1])
        t_ed = timed(compiled, case.make()[1])
        ratios.append(t_base / t_ed)
        times.append(t_ed)
    ratio = sorted(ratios)[1]
    t_ed = sorted(times)[1]
    return {
        "metric": f"{case.name}_items_per_sec",
        "value": round(tokens_per_step / t_ed, 1),
        "unit": "items/s",
        "vs_baseline": round(ratio, 4),
    }


def main():
    from benchmark.bench_cases import all_cases

    ap = argparse.ArgumentParser()
    ap.add_argument("--cases", default=None,
                    help="comma-separated case names (default: all)")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    tpu = jax.default_backend() == "tpu"
    cases = all_cases(tpu)
    if args.cases:
        wanted = set(args.cases.split(","))
        cases = [c for c in cases if c.name in wanted]
    for case in cases:
        try:
            print(json.dumps(bench_case(case, iters=args.iters)), flush=True)
        except Exception as e:  # keep sweeping
            print(json.dumps({"metric": case.name, "error": str(e)[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
