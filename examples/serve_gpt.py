"""Serve a GPT model with continuous batching over easydist auto-parallel.

Demonstrates `easydist_tpu.serve.ServeEngine` end-to-end: compile the GPT
forward once per shape bucket with `easydist_compile`, warm the buckets
eagerly, then drive the engine with concurrent synthetic clients and print
the serving metrics (throughput, batch occupancy, cache hit rate,
p50/p95/p99 latency).

Runs anywhere: on a real TPU mesh it serves the sharded program; on CPU it
uses the host devices (JAX_PLATFORMS=cpu works for a laptop demo).

    python examples/serve_gpt.py [--clients 8] [--requests 12] [--small]
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import jax

from easydist_tpu.jaxfront import easydist_compile, make_device_mesh
from easydist_tpu.models.gpt import GPTConfig, gpt_apply, gpt_init
from easydist_tpu.serve import ServeConfig, ServeEngine


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=12,
                    help="requests per client")
    ap.add_argument("--small", action="store_true",
                    help="GPT-2 small instead of the tiny smoke config")
    ap.add_argument("--max-wait-ms", type=float, default=8.0)
    args = ap.parse_args()

    cfg = GPTConfig.small() if args.small else GPTConfig.tiny()
    seq_buckets = (cfg.seq // 4, cfg.seq // 2, cfg.seq) if args.small \
        else (16, 32)
    params = gpt_init(cfg, jax.random.PRNGKey(0))
    mesh = make_device_mesh((len(jax.devices()),), ("d",))

    def infer(p, tokens):
        return gpt_apply(p, cfg, tokens)

    compiled = easydist_compile(infer, mesh=mesh, state_io={})
    engine = ServeEngine(
        compiled,
        ServeConfig(batch_buckets=(4, 8), seq_buckets=seq_buckets,
                    max_wait_ms=args.max_wait_ms, max_queue=512,
                    default_deadline_ms=60_000.0),
        state=params)

    print(f"# warming {2 * len(seq_buckets)} buckets "
          f"(batch 4,8 x seq {seq_buckets}) ...", file=sys.stderr)
    t0 = time.time()
    warmed = engine.warmup((np.zeros((seq_buckets[0],), np.int32),))
    print(f"# warmed {warmed} bucket shapes in {time.time() - t0:.1f}s",
          file=sys.stderr)

    errors = []

    def client(cid):
        rng = np.random.RandomState(cid)
        try:
            for _ in range(args.requests):
                n = int(rng.randint(4, max(seq_buckets) + 1))
                toks = rng.randint(0, cfg.vocab, (n,)).astype(np.int32)
                logits = engine.infer(toks, timeout=120)
                assert logits.shape == (n, cfg.vocab)
                # open-loop-ish think time so batches interleave
                time.sleep(float(rng.uniform(0, 0.01)))
        except Exception as e:  # noqa: BLE001 - demo reporting
            errors.append((cid, repr(e)))

    t0 = time.time()
    with engine:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        stats = engine.stats()
        engine.export_metrics(sub_key="serve_gpt_example")

    done = stats["counters"].get("requests_completed", 0)
    lat = stats["latency"]["e2e"]
    print(json.dumps({
        "requests_completed": done,
        "errors": errors,
        "throughput_req_s": round(done / wall, 2),
        "batch_occupancy": round(stats["batch_occupancy"] or 0.0, 3),
        "compile_cache_hit_rate": round(
            stats["compile_cache_hit_rate"] or 0.0, 3),
        "distinct_executables": stats["distinct_executables"],
        "p50_ms": round(1e3 * (lat.get("p50_s") or 0.0), 2),
        "p95_ms": round(1e3 * (lat.get("p95_s") or 0.0), 2),
        "p99_ms": round(1e3 * (lat.get("p99_s") or 0.0), 2),
    }, indent=1))
    if errors:
        sys.exit(1)


if __name__ == "__main__":
    main()
