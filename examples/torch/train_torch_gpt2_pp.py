"""Real HF GPT-2 trained pipeline-parallel through the torch frontend
(reference: easydist/torch/experimental/pp/api.py — per-rank NCCL
schedules there; one compiled SPMD program here).

python examples/torch/train_torch_gpt2_pp.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
os.environ.setdefault("HF_HUB_OFFLINE", "1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import torch  # noqa: E402


def main():
    from transformers import GPT2Config, GPT2LMHeadModel

    from easydist_tpu.jaxfront import make_device_mesh
    from easydist_tpu.torchfront import make_torch_pp_train_step

    torch.manual_seed(0)
    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=64, n_layer=4,
                     n_head=4, resid_pdrop=0.0, embd_pdrop=0.0,
                     attn_pdrop=0.0)
    model = GPT2LMHeadModel(cfg).train()

    class LM(torch.nn.Module):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, ids):
            return self.m(input_ids=ids).logits

    def xent(logits, targets):
        logp = jax.nn.log_softmax(logits, axis=-1)
        oh = jax.nn.one_hot(targets, logits.shape[-1])
        return -jnp.mean(jnp.sum(oh * logp, axis=-1))

    mesh = make_device_mesh((4, 2), ("pp", "dp"))
    ids = torch.randint(0, cfg.vocab_size, (16, 32))
    compiled, params0 = make_torch_pp_train_step(
        LM(model), (ids,), xent, mesh, pp_stages=4, n_microbatches=2,
        lr=1e-3, train=True, schedule="1f1b")

    j_in = jnp.asarray(ids.numpy())
    state = compiled.init_state(params0, j_in, j_in)
    for i in range(5):
        state, loss = compiled(state, j_in, j_in)
        print(f"step {i}: loss {float(loss):.5f}")


if __name__ == "__main__":
    main()
