"""Train an unmodified PyTorch module on TPU/XLA — no CUDA, no NCCL
(reference: examples/torch/simple_function.py + north-star requirement).

python examples/torch/train_torch_mlp.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402

import jax.numpy as jnp
import torch
import torch.nn as nn


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.seq = nn.Sequential(
            nn.Linear(64, 256), nn.ReLU(), nn.LayerNorm(256),
            nn.Linear(256, 10))

    def forward(self, x):
        return self.seq(x)


def main():
    from easydist_tpu.jaxfront import make_device_mesh
    from easydist_tpu.torchfront import make_torch_train_step

    make_device_mesh()
    module = Net()
    x_example = torch.randn(128, 64)

    def ce(pred, labels):
        logp = jax.nn.log_softmax(pred, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

    step, init_state = make_torch_train_step(
        module, (x_example,), ce, optimizer="adam", lr=1e-3)
    state = init_state()

    key = jax.random.PRNGKey(0)
    for i in range(10):
        key, k1, k2 = jax.random.split(key, 3)
        x = jax.random.normal(k1, (128, 64))
        y = jax.random.randint(k2, (128,), 0, 10)
        state, loss = step(state, x, y)
        if i % 3 == 0:
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
