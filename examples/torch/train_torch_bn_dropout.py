"""Training a torch module with dropout + batch-norm on the TPU mesh:
training-mode export threads a jax PRNG into dropout and batch-norm
running stats through the train state (reference torch/compile.py:25-95).

python examples/torch/train_torch_bn_dropout.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import torch  # noqa: E402
import torch.nn as nn  # noqa: E402

from easydist_tpu.jaxfront import make_device_mesh  # noqa: E402
from easydist_tpu.torchfront import make_torch_train_step  # noqa: E402


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(64, 128)
        self.bn = nn.BatchNorm1d(128)
        self.drop = nn.Dropout(0.1)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(self.drop(torch.relu(self.bn(self.fc1(x)))))


def main():
    mesh = make_device_mesh((8,), ("d",))
    torch.manual_seed(0)
    module = Net()
    x = torch.randn(256, 64)
    y = torch.randn(256, 10)

    # a real torch optimizer: hyperparams (and any warm Adam state)
    # translate into the jax update
    opt = torch.optim.Adam(module.parameters(), lr=1e-3)

    step, init_state = make_torch_train_step(
        module, (x,), lambda out, t: jnp.mean((out - t) ** 2),
        optimizer=opt, mesh=mesh, train=True, donate_state=False)
    state = init_state()
    jx, jy = jnp.asarray(x.numpy()), jnp.asarray(y.numpy())
    for i in range(5):
        state, loss = step(state, jax.random.PRNGKey(i), jx, jy)
        print(f"step {i}: loss {float(loss):.4f}")
    (trainable, buffers), _ = state
    print("running mean drifted:",
          float(jnp.abs(buffers["bn.running_mean"]).mean()))


if __name__ == "__main__":
    main()
