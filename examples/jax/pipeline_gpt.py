"""Pipeline-parallel transformer blocks over a pp mesh axis
(reference: benchmark/torch/pp/gpt/speed/easydist_pipeline.py).

python examples/jax/pipeline_gpt.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402

import jax.numpy as jnp


def main():
    from easydist_tpu.jaxfront import make_device_mesh
    from easydist_tpu.parallel import PipelineConfig, spmd_pipeline
    from easydist_tpu.parallel.pipeline import stack_stage_params

    S, M, mb, d = 4, 8, 4, 128
    mesh = make_device_mesh((S, 2), ("pp", "dp"),
                            devices=jax.devices()[:S * 2])

    def stage_fn(p, x):
        h = jnp.tanh(x @ p["w1"])
        return x + h @ p["w2"]

    keys = jax.random.split(jax.random.PRNGKey(0), S)
    stages = [{"w1": jax.random.normal(k, (d, 4 * d)) / jnp.sqrt(d),
               "w2": jax.random.normal(k, (4 * d, d)) / jnp.sqrt(4 * d)}
              for k in keys]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d))

    pipe = jax.jit(spmd_pipeline(
        stage_fn, mesh, PipelineConfig(S, M, data_axis="dp")))
    out = pipe(stacked, x)
    print("pipeline output:", out.shape, "finite:", bool(jnp.isfinite(out).all()))


if __name__ == "__main__":
    main()
