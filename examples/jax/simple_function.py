"""Smallest end-to-end example: one decorator auto-parallelizes a function
(reference: examples/jax/simple_function.py).

Run on any host:  python examples/jax/simple_function.py
(uses the 8-device virtual CPU mesh when no TPU is attached)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402

import jax.numpy as jnp

from easydist_tpu import easydist_compile
from easydist_tpu.jaxfront import make_device_mesh


@easydist_compile()
def step(w, x):
    return jnp.tanh(x @ w).sum()


def main():
    make_device_mesh()  # 1D mesh over every visible device
    w = jax.random.normal(jax.random.PRNGKey(0), (512, 256))
    x = jax.random.normal(jax.random.PRNGKey(1), (1024, 512))
    out = step(w, x)
    print("result:", float(out))
    result = step.get_compiled(w, x)
    print("input shardings:", [str(s.spec) for s in result.in_shardings])


if __name__ == "__main__":
    main()
