"""Expert-parallel MoE layer over an ep mesh axis.

python examples/jax/moe_layer.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    from easydist_tpu.jaxfront import make_device_mesh
    from easydist_tpu.parallel.moe import MoEConfig, moe_init, moe_layer

    n = len(jax.devices())
    mesh = make_device_mesh((n,), ("ep",))
    cfg = MoEConfig(n_experts=2 * n, d_model=64, d_ff=256,
                    capacity_factor=1.5)
    params = moe_init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.normal(jax.random.PRNGKey(1), (64 * n, cfg.d_model))

    y, aux = jax.jit(lambda p, x: moe_layer(p, x, mesh, cfg))(params, tokens)
    print(f"MoE over {n} devices, {cfg.n_experts} experts: "
          f"out {y.shape}, load-balance aux {float(aux):.4f}")


if __name__ == "__main__":
    main()
