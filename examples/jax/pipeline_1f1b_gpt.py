"""1F1B (DAPPLE-class) pipelined GPT training with interleaved virtual
stages — O(n_stages) live microbatches instead of GPipe's O(M)
(reference: ScheduleDAPPLE, torch/experimental/pp/runtime.py:658-700).

python examples/jax/pipeline_1f1b_gpt.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402

from easydist_tpu.jaxfront import make_device_mesh  # noqa: E402
from easydist_tpu.models import GPTConfig  # noqa: E402
from easydist_tpu.models.gpt import make_gpt_pipeline_step  # noqa: E402


def main():
    # 4 pipeline stages x 2-way data parallel; each device runs TWO virtual
    # stage chunks (8 chunks total) to shrink the pipeline bubble
    mesh = make_device_mesh((4, 2), ("pp", "dp"))
    cfg = GPTConfig.tiny(layers=8)
    M = 8  # microbatches

    step, init_state = make_gpt_pipeline_step(
        cfg, mesh, n_microbatches=M, schedule="1f1b", n_virtual=2,
        data_axis="dp", lr=1e-3)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (M, 2, cfg.seq), 0,
                                cfg.vocab)

    step = jax.jit(step)
    for i in range(5):
        state, loss = step(state, tokens, tokens)
        print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
