"""Llama-style training with auto-parallelization + the C++ token loader.

python examples/jax/train_llama.py [--steps 5]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402
import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    from easydist_tpu import easydist_compile
    from easydist_tpu.jaxfront import make_device_mesh
    from easydist_tpu.models import LlamaConfig, make_llama_train_step
    from easydist_tpu.runtime.data import TokenLoader

    n = len(jax.devices())
    mesh = make_device_mesh((n // 2, 2) if n >= 4 else (n,),
                            ("dp", "tp") if n >= 4 else ("dp",))

    cfg = LlamaConfig.tiny()
    step, init_state = make_llama_train_step(cfg, lr=3e-4)
    compiled = easydist_compile(step, mesh=mesh)
    state = init_state(jax.random.PRNGKey(0))

    # synthetic token file fed through the native prefetching loader
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "tokens.bin")
        np.random.default_rng(0).integers(
            0, cfg.vocab, 100_000).astype(np.uint16).tofile(path)
        loader = TokenLoader(path, batch=8, seq=cfg.seq)
        for i, (x, y) in zip(range(args.steps), loader):
            state, loss = compiled(state, x, y)
            print(f"step {i}: loss {float(loss):.4f}")
        loader.close()


if __name__ == "__main__":
    main()
