"""One-decorator hybrid: auto-split pipeline stages x ZeRO-dp x
solver-chosen tensor parallelism, from an UNMODIFIED loss function
(reference: the schedule_cls path of easydist_compile,
torch/compile_auto.py:683-715).

python examples/jax/hybrid_pp_tp.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main():
    from jax.sharding import Mesh

    from easydist_tpu.jaxfront import easydist_compile

    # pp pipelines the depth, dp splits the batch, tp splits the wide
    # matmuls inside each stage (the per-axis ILP decides which ones pay)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "dp", "tp"))

    D = 1024
    keys = jax.random.split(jax.random.PRNGKey(0), 8)
    params = {f"w{i}": jax.random.normal(keys[i], (D, D)) * 0.02
              for i in range(6)}

    def loss_fn(params, x, y):       # plain jax — no sharding anywhere
        h = x
        for i in range(6):
            h = jnp.tanh(h @ params[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    step = easydist_compile(loss_fn, mesh=mesh, pp_stages=2,
                            n_microbatches=4, lr=1e-3,
                            tp_axes=("tp",), schedule="1f1b")

    x = jax.random.normal(keys[6], (32, D))
    y = jax.random.normal(keys[7], (32, D))
    state = step.init_state(params, x, y)   # packs + ZeRO-shards

    (packed, _), _ = state
    n_dev = len(mesh.devices.flatten())
    print(f"param bytes/device: {packed.addressable_shards[0].data.nbytes}"
          f" of {packed.nbytes} total (1/{n_dev})")
    print(f"solver tensor-sharded {step.tp_summary()['sharded']} eqns "
          f"inside the stages")

    for i in range(5):
        state, loss = step(state, x, y)
        print(f"step {i}: loss {float(loss):.5f}")


if __name__ == "__main__":
    main()
