"""GPT training with automatic parallelization + elastic checkpointing
(reference: examples/jax/test_gpt.py and benchmark/torch/pp/gpt/).

python examples/jax/train_gpt.py [--steps 20] [--tiny]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


if not os.environ.get("EASYDIST_REAL_DEVICES"):
    from easydist_tpu.utils.testing import force_cpu_devices

    force_cpu_devices(8)
import jax  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tiny", action="store_true", default=True)
    ap.add_argument("--ckpt", default="/tmp/easydist_gpt_ckpt")
    args = ap.parse_args()

    from easydist_tpu import easydist_compile
    from easydist_tpu.jaxfront import make_device_mesh
    from easydist_tpu.models import GPTConfig, make_gpt_train_step
    from easydist_tpu.runtime import run_training

    n = len(jax.devices())
    mesh = make_device_mesh((n // 2, 2) if n >= 4 else (n,),
                            ("dp", "tp") if n >= 4 else ("dp",))

    cfg = GPTConfig.tiny() if args.tiny else GPTConfig()
    step, init_state = make_gpt_train_step(cfg, lr=1e-3)
    compiled = easydist_compile(step, mesh=mesh)

    def data():
        key = jax.random.PRNGKey(0)
        while True:
            key, k1 = jax.random.split(key)
            toks = jax.random.randint(k1, (8, cfg.seq), 0, cfg.vocab)
            yield toks[:, :], toks[:, :]  # predict-same toy objective

    losses = []
    state = run_training(compiled, lambda: init_state(jax.random.PRNGKey(0)),
                         data(), args.ckpt, total_steps=args.steps,
                         checkpoint_every=5,
                         on_step=lambda s, l: losses.append(float(l)))
    if losses:
        print(f"trained {len(losses)} steps; "
              f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    else:
        print(f"checkpoint already at step {args.steps}; nothing to do "
              f"(state restored OK)")


if __name__ == "__main__":
    main()
