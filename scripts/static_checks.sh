#!/usr/bin/env bash
# Tier-1-adjacent static gate: ruff + mypy over easydist_tpu/, configured
# in pyproject.toml (scoped, baseline-clean, no blanket ignores).
#
# Run from the repo root:  bash scripts/static_checks.sh
# Exit code is nonzero iff an installed tool reports findings; a missing
# tool is reported and skipped (the hermetic CI image does not ship them —
# install with `pip install ruff mypy` where allowed).
set -u
cd "$(dirname "$0")/.."
rc=0
ran=0

if command -v ruff >/dev/null 2>&1; then
    ran=1
    echo "== ruff check easydist_tpu"
    ruff check easydist_tpu || rc=1
else
    echo "static_checks: ruff not installed; skipping (pip install ruff)"
fi

if command -v mypy >/dev/null 2>&1; then
    ran=1
    echo "== mypy easydist_tpu"
    mypy --config-file pyproject.toml || rc=1
else
    echo "static_checks: mypy not installed; skipping (pip install mypy)"
fi

# the sharding/memory/schedule lint ships in-tree but needs a jax to trace
# the preset models: bench.py --analyze gates zero error-severity findings
# (STRAT/COLL plus the MEM/SCHED memory-plan & pipeline-schedule rules and
# the HBM-budget/peak-drift assertions) when jax is importable, and skips
# gracefully where it is not (bare linting containers)
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --analyze (sharding + memory/schedule lint gate)"
    out=$(python bench.py --analyze 2>/dev/null) || rc=1
    echo "$out"
    errors=$(python - "$out" <<'EOF'
import json, sys
try:
    print(json.loads(sys.argv[1].strip().splitlines()[-1])["value"])
except Exception:
    print(-1)
EOF
)
    if [ "$errors" != "0" ]; then
        echo "static_checks: sharding lint reported $errors error finding(s)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --analyze"
fi

# analyzer driver gate (docs/ANALYZE.md "Driver"): the layer-11 host
# donation lint + the preset analyze stack behind the shared driver —
# inline suppressions and the committed baseline (analyze_baseline.json)
# applied, SARIF artifact emitted for CI, incremental cache warm across
# repeat runs.  Fails on any NON-BASELINED error; refresh the baseline
# with `python -m easydist_tpu.analyze --refresh-baseline` (see README).
if python -c "import jax" >/dev/null 2>&1; then
    echo "== python -m easydist_tpu.analyze (driver gate: ast + presets + protocol)"
    mkdir -p "${EASYDIST_ARTIFACT_DIR:-/tmp/easydist_artifacts}"
    sarif="${EASYDIST_ARTIFACT_DIR:-/tmp/easydist_artifacts}/analyze.sarif"
    python -m easydist_tpu.analyze --targets ast,presets,protocol \
        --sarif "$sarif" || {
        echo "static_checks: analyzer driver reported new (non-baselined)" \
             "error finding(s)"
        rc=1
    }
    [ -s "$sarif" ] && echo "static_checks: SARIF artifact at $sarif"
else
    echo "static_checks: jax not importable; skipping the analyzer driver"
fi

# protocol model-check gate (docs/ANALYZE.md layer 12): exhaustively
# explore the four fleet protocol specs (health, router, resume,
# transport — analyze/modelcheck.py) over EVERY interleaving at their
# committed scope.  Needs no jax, so it runs even in bare containers.
# The exploration is bounded twice over: a hard wall-clock timeout here,
# and the committed per-spec state budgets inside — exhausting more (or
# fewer) states than COMMITTED_STATES by >20% is a PROTO003 error (the
# spec changed shape without a conscious budget re-commit), and any
# PROTO001 safety violation / PROTO002 stuck state fails the gate with
# its shortest counterexample trace in the output.
echo "== python -m easydist_tpu.analyze --targets protocol (model-check gate)"
proto_json="${EASYDIST_ARTIFACT_DIR:-/tmp/easydist_artifacts}/protocol.json"
mkdir -p "$(dirname "$proto_json")"
if timeout 120 python -m easydist_tpu.analyze --targets protocol \
        --no-cache --json "$proto_json"; then
    python - "$proto_json" <<'PYEOF'
import json, sys
d = json.load(open(sys.argv[1]))
for name, st in sorted(d.get("protocol", {}).items()):
    print(f"static_checks: protocol[{name}] {st['states']} states "
          f"(committed {st['committed']}, exhausted={st['exhausted']})")
PYEOF
else
    echo "static_checks: protocol model-check gate FAILED (safety" \
         "violation, stuck state, budget drift >20%, or timeout)"
    rc=1
fi

# overlapped-collectives gate: the backward-ordered barrier-pinned flush
# must stay bitwise-identical to the sequential one (quantization off) and
# the emission-ordered bucket chain must expose a nonzero SCHEDULABLE
# overlap fraction (bench.py --overlap `value`; program-structure bound,
# deterministic — measured wall-clock fractions and step-time deltas on
# virtual CPU meshes are noise, so only the deterministic bits gate)
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --overlap (overlapped-flush parity gate)"
    out=$(python bench.py --overlap 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'EOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("parity_bitwise"):
        print("parity_bitwise false")
    elif not r.get("value", 0) > 0:
        print("overlap_fraction not > 0")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
EOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: overlap gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --overlap"
fi

# resilience gate: every drill in bench.py --resilience is deterministic
# (injected faults, bitwise recovery checks, trace-identity audit), so the
# whole JSON record gates — value 1.0 means torn writes stayed invisible,
# the preempted run resumed bitwise-identical, the guard-off trace matched
# the default build, and the serve watchdog recovered after an injected
# execute timeout
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --resilience (fault-injection recovery gate)"
    out=$(python bench.py --resilience 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'EOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif r.get("value") != 1.0:
        print("recovery drill value != 1.0")
    elif not r.get("guard_off_trace_identical"):
        print("guard-off trace not identical")
    elif not r.get("ckpt_torn_write_invisible"):
        print("torn checkpoint write became visible")
    elif not r.get("preempt_resume_bitwise"):
        print("preempt resume not bitwise-identical")
    elif not r.get("serve_watchdog_recovered"):
        print("serve watchdog did not recover")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
EOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: resilience gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --resilience"
fi

# decode-serving gate: KV-cached generation must beat the naive full
# re-forward greedy loop >= 5x in tokens/s at seq 512 (the O(T) vs O(T^2)
# economics), with bitwise greedy parity and a decode signature cache that
# stays at one compiled step per bucket across every generated token.
# The mixed-length section additionally gates the paged KV layout: paged
# greedy ids bitwise == bucketed, ONE compiled paged decode step for every
# length, tokens/s at or above the bucketed pools, slot bytes/seq strictly
# below them, and a zero-copy prefix restore (copy_on_restore_bytes_saved)
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --decode (KV-cache decode speedup + parity gate)"
    out=$(python bench.py --decode 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'EOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("parity_greedy"):
        print("cached greedy ids diverge from full re-forward")
    elif not r.get("signature_cache_constant"):
        print("decode signature cache grew across tokens")
    elif not r.get("value", 0) >= 5.0:
        print(f"speedup {r.get('value')} < 5.0x")
    elif not r.get("paged_parity_greedy"):
        print("paged greedy ids diverge from bucketed")
    elif not r.get("paged_signature_constant"):
        print("paged decode signature cache grew across mixed lengths")
    elif not r.get("paged_tokens_per_s", 0) >= r.get("bucketed_tokens_per_s", 1e18):
        print(f"paged {r.get('paged_tokens_per_s')} tok/s below bucketed "
              f"{r.get('bucketed_tokens_per_s')}")
    elif not r.get("paged_bytes_per_seq", 1e18) < r.get("bucketed_bytes_per_seq", 0):
        print(f"paged bytes/seq {r.get('paged_bytes_per_seq')} not below "
              f"bucketed {r.get('bucketed_bytes_per_seq')}")
    elif not r.get("copy_on_restore_bytes_saved", 0) > 0:
        print("paged prefix restore saved zero copy bytes")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
EOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: decode gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --decode"
fi

# chunked-prefill / prefix-cache gate: restoring a shared 256-token
# prefix from the trie must cut TTFT >= 2x vs recomputing it (cache-off),
# with bitwise greedy parity cache-on vs cache-off vs full re-forward and
# ONE compiled chunk program per bucket across all prompt lengths
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --prefill (prefix-cache TTFT speedup + parity gate)"
    out=$(python bench.py --prefill 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("parity_greedy"):
        print("cache-on greedy ids diverge from cache-off")
    elif not r.get("parity_vs_full_forward"):
        print("greedy ids diverge from the full re-forward reference")
    elif not r.get("signature_cache_constant"):
        print("prefill signature cache grew across prompt lengths")
    elif not r.get("value", 0) >= 2.0:
        print(f"TTFT speedup {r.get('value')} < 2.0x")
    elif not r.get("paged_parity_greedy"):
        print("paged-layout greedy ids diverge from the bucketed cache-on run")
    elif not r.get("copy_on_restore_bytes_saved", 0) > 0:
        print("paged prefix restore saved zero copy bytes")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: prefill gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --prefill"
fi

# fleet-serving gate: multi-replica routing must keep bitwise greedy
# parity with the single session (including the disaggregated-prefill and
# drain-mid-traffic arms), the affinity policy must beat uniform-random
# on the aggregate prefix-trie hit rate, and a graceful drain under live
# load must drop zero requests
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --fleet (multi-replica routing + drain gate)"
    out=$(python bench.py --fleet 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("parity_greedy"):
        print("fleet greedy ids diverge from the single-session run")
    elif not r.get("affinity_beats_random"):
        print(f"affinity hit rate {r.get('value')} does not beat random "
              f"{r.get('random_hit_rate')}")
    elif not r.get("drain_zero_drop"):
        print(f"drain dropped {r.get('drain_dropped_requests')} request(s)")
    elif not r.get("prefill_handoffs", 0) > 0:
        print("disaggregated prefill never handed off a page")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: fleet gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --fleet"
fi

# fleet-chaos gate: a seeded fault schedule kills one replica per
# traffic wave mid-decode (revived between waves); every stream must
# still finish bitwise-identical to the single-session run with zero
# dropped requests, at least one request actually recovered from its
# ResumeDescriptor, every scheduled fault fired (a drill whose faults
# never fired tested nothing), a clean FLEET001/004 routing audit, and
# TTFT p99 within the bounded multiple of the calm arm
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --fleet-chaos (crash/revive recovery drill gate)"
    out=$(python bench.py --fleet-chaos 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("parity_bitwise"):
        print("chaos-arm greedy ids diverge from the single-session run")
    elif r.get("dropped_requests", 1) != 0:
        print(f"chaos drill dropped {r.get('dropped_requests')} request(s)")
    elif not r.get("requests_recovered", 0) > 0:
        print("no request was ever recovered (drill tested nothing)")
    elif r.get("replica_crashes") != r.get("crashes_scheduled"):
        print(f"observed {r.get('replica_crashes')} crash(es), scheduled "
              f"{r.get('crashes_scheduled')}")
    elif r.get("fault_plan_unfired", 1) != 0:
        print(f"{r.get('fault_plan_unfired')} scheduled fault(s) never fired")
    elif r.get("routing_findings", 1) != 0:
        print(f"routing audit raised {r.get('routing_findings')} "
              f"FLEET001/004 finding(s)")
    elif r.get("proto_findings", 1) != 0:
        print(f"protocol conformance replay raised "
              f"{r.get('proto_findings')} PROTO003 finding(s) — the "
              f"drill's transitions() streams drifted from the specs")
    elif not r.get("ttft_p99_inflation", 1e18) <= r.get("ttft_p99_bound", 0):
        print(f"ttft p99 inflated {r.get('ttft_p99_inflation')}x under "
              f"chaos (bound {r.get('ttft_p99_bound')}x)")
    elif not r.get("verify_steps", 0) > 0:
        print("no speculative verify round was in flight during the drill")
    elif not r.get("int8_wave_parity"):
        print("int8 wave: quantized crash-resume diverged from the "
              "single-session int8 reference (re-prefilled pages must "
              "rebuild bitwise)")
    elif r.get("int8_wave_dropped", 1) != 0 \
            or not r.get("int8_wave_recovered", 0) > 0 \
            or r.get("int8_wave_crashes") != 1 \
            or r.get("int8_wave_unfired", 1) != 0:
        print(f"int8 wave drill incomplete (dropped="
              f"{r.get('int8_wave_dropped')}, recovered="
              f"{r.get('int8_wave_recovered')}, crashes="
              f"{r.get('int8_wave_crashes')}, unfired="
              f"{r.get('int8_wave_unfired')})")
    elif r.get("value") != 1.0:
        print(f"only {r.get('value')} of requests finished clean")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: fleet-chaos gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --fleet-chaos"
fi

# kv-scale gate: the quantized + host-tiered paged-KV economics.  The
# int8 arm must admit >= 1.8x the sequences per HBM byte, agree with the
# exact arm >= 0.995 (free-running greedy AND teacher-forced) under a
# bounded logit drift; the exact arm must stay bitwise with a scale-free
# arena (quant off is the pre-quant program); the host tier must restore
# >= 0.9 of its prefix tokens at a 10x-HBM working set with zero sha256
# manifest failures; and both kv.tier fault points must drill live with
# every scheduled fault fired
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --kv-scale (quantized + tiered KV density gate)"
    out=$(python bench.py --kv-scale 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("exact_bitwise"):
        print("exact paged arm diverged from the bucketed session "
              "(quant-off must stay bitwise)")
    elif not r.get("exact_scale_free"):
        print("exact arm's arena carries scale leaves or int8 payloads "
              "(quant-off purity broken)")
    elif not r.get("value", 0) >= r.get("ratio_floor", 1.8):
        print(f"int8 density {r.get('value')}x below the "
              f"{r.get('ratio_floor')}x slots-per-HBM-byte floor")
    elif not r.get("greedy_match", 0) >= r.get("match_floor", 0.995) \
            or not r.get("teacher_forced_match", 0) >= \
            r.get("match_floor", 0.995):
        print(f"int8 A/B agreement below floor (greedy "
              f"{r.get('greedy_match')}, teacher-forced "
              f"{r.get('teacher_forced_match')}, floor "
              f"{r.get('match_floor')})")
    elif not r.get("logit_drift_max", 1e18) <= \
            r.get("logit_drift_bound", 0):
        print(f"int8 logit drift {r.get('logit_drift_max')} exceeds "
              f"bound {r.get('logit_drift_bound')}")
    elif not r.get("tier_hit_rate", 0) >= r.get("tier_hit_floor", 0.9):
        print(f"tier hit rate {r.get('tier_hit_rate')} below "
              f"{r.get('tier_hit_floor')} at "
              f"{r.get('tier_working_set_x')}x HBM working set")
    elif r.get("tier_manifest_failures", 1) != 0:
        print(f"{r.get('tier_manifest_failures')} tier manifest "
              f"failure(s) — host pages round-tripped corrupt")
    elif not r.get("tier_pass_bitwise") \
            or not r.get("tier_invariants_clean"):
        print("tiered pass diverged or tier/trie invariants dirty")
    elif r.get("drill_fetch_corrupt_unfired", 1) != 0 \
            or r.get("drill_host_oom_unfired", 1) != 0:
        print("a scheduled kv.tier fault never fired (drill tested "
              "nothing)")
    elif not r.get("tier_fetch_retries", 0) >= 1 \
            or not r.get("drill_host_oom_paused"):
        print("kv.tier drills left no footprint (no manifest-caught "
              "refetch, or OOM never paused demotion)")
    elif r.get("verdict") != "ok":
        print(f"scenario verdict {r.get('verdict')}")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% "
              f"below last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: kv-scale gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --kv-scale"
fi

# elastic-chaos gate: train on 8 virtual devices, take a mesh-shrink
# SIGTERM mid-run, restart on a 4-device sub-mesh (newest checkpoint
# corrupted -> one-step fallback + replay), grow back to 8 (restore
# chunk budget "OOMs" -> halve and replan); the full loss stream AND
# final state must be bitwise-identical to an uninterrupted 8-device
# run, both restores must detect the topology shift and route through
# the reshard planner inside the RESHARD001 byte bound with zero
# findings, and every scheduled fault must fire
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --elastic-chaos (topology-shift recovery drill gate)"
    out=$(python bench.py --elastic-chaos 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("final_state_bitwise"):
        print("final state diverges from the uninterrupted 8-device run")
    elif not r.get("loss_stream_bitwise"):
        print(f"loss stream diverges at {r.get('loss_mismatches')}")
    elif not r.get("shrink_notice_preempted"):
        print("mesh-shrink notice never preempted the loop")
    elif r.get("fault_plan_unfired", 1) != 0:
        print(f"{r.get('fault_plan_unfired')} scheduled fault(s) never fired")
    elif r.get("topology_shifts_detected") != 2:
        print(f"detected {r.get('topology_shifts_detected')} topology "
              f"shift(s), expected 2 (8->4 and 4->8)")
    elif not r.get("restore_peak_within_bound"):
        print("a restore plan's peak live bytes exceeded the chunked bound")
    elif r.get("reshard_findings", 1) != 0:
        print(f"{r.get('reshard_findings')} RESHARD001/002 finding(s)")
    elif r.get("proto_findings", 1) != 0:
        print(f"restore-attempt conformance replay raised "
              f"{r.get('proto_findings')} PROTO003 finding(s)")
    elif not r.get("steps_replayed_after_fallback"):
        print("corrupt-checkpoint fallback replayed no step "
              "(drill tested nothing)")
    elif r.get("value") != 1.0:
        print("drill gate value != 1.0")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: elastic-chaos gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --elastic-chaos"
fi

# speculative-decoding gate: draft/verify greedy decode must beat plain
# decode >= 1.4x tokens/s on the repetitive (hot-prompt) workload and
# slow the adversarial (always-rejected-drafts) workload by <= 1.15x,
# with bitwise greedy parity on BOTH workloads (the accept rule is
# self-validating), ONE compiled verify signature, and the paged
# mini-arm's spill-page rollback actually releasing pages
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --speculate (draft/verify speedup + parity gate)"
    out=$(python bench.py --speculate 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif not r.get("parity_greedy"):
        print("speculative greedy ids diverge from plain decode")
    elif not r.get("paged_parity_greedy"):
        print("paged speculative greedy ids diverge from plain decode")
    elif not r.get("verify_signature_constant"):
        print("verify signature cache grew past one compiled step")
    elif not r.get("value", 0) >= 1.4:
        print(f"repetitive speedup {r.get('value')} < 1.4x")
    elif not r.get("adversarial_slowdown", 1e18) <= r.get(
            "adversarial_slowdown_bound", 0):
        print(f"adversarial slowdown {r.get('adversarial_slowdown')}x over "
              f"bound {r.get('adversarial_slowdown_bound')}x")
    elif not r.get("speculative_rollback_pages_released", 0) > 0:
        print("paged rollback released zero spill pages (arm tested nothing)")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: speculate gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --speculate"
fi

# simulator-validation gate: every held-out validation preset's predicted
# time must land within the committed relative-error bound of the bench
# actual measured on THIS host (calibration presets fit the per-domain
# residual and are excluded), with zero SIM001 analyze findings
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --simulate (calibrated-simulator validation gate)"
    out=$(python bench.py --simulate 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif r.get("value", 0) < r.get("n_validation_presets", 4):
        print(f"only {r.get('value')}/{r.get('n_validation_presets')} "
              f"validation presets within the "
              f"{r.get('rel_error_bound')} bound "
              f"(worst rel err {r.get('worst_rel_error')})")
    elif r.get("sim_findings", 1) != 0:
        print(f"{r.get('sim_findings')} SIM001 finding(s) on the "
              f"validation rows")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: simulate gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --simulate"
fi

# autoscale ramp-drill gate: the deterministic ramp-up/hold/ramp-down
# drill must drop zero requests, keep committed tokens bitwise-identical
# to the fixed-fleet reference, converge each phase to the capacity
# planner's independently computed target, log zero SIM002 flap
# findings, and degrade gracefully (hold + loud warning, still zero
# drops, still bitwise) under both catalogued autoscale fault points
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --autoscale (SLO-autoscaler ramp drill gate)"
    out=$(python bench.py --autoscale 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif r.get("dropped_requests", 1) != 0:
        print(f"ramp drill dropped {r.get('dropped_requests')} request(s)")
    elif not r.get("parity_bitwise"):
        print("scaled-fleet ids diverge from the fixed-fleet run")
    elif not r.get("targets_match_planner"):
        print(f"phase replica counts {r.get('phase_replicas')} do not "
              f"match planner targets (high={r.get('planner_target_high')}"
              f", low={r.get('planner_target_low')})")
    elif r.get("flap_findings", 1) != 0:
        print(f"{r.get('flap_findings')} SIM002 flap finding(s) in the "
              f"decision log")
    elif not (r.get("stale_arm", {}).get("drops", 1) == 0
              and r.get("stale_arm", {}).get("bitwise")):
        print(f"stale-metrics arm degraded unsafely: {r.get('stale_arm')}")
    elif not (r.get("scaleup_fail_arm", {}).get("drops", 1) == 0
              and r.get("scaleup_fail_arm", {}).get("bitwise")):
        print("scale-up-failure arm degraded unsafely: "
              f"{r.get('scaleup_fail_arm')}")
    elif r.get("value", 0) != 1.0:
        print(f"ramp survival {r.get('value')} != 1.0")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: autoscale gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --autoscale"
fi

# pruned-discovery gate: propagation groups + batched probes + the
# persistent rule cache must cut execution-discovery probe compiles
# >=5x cold and >=10x warm across the four-variant gpt recompile
# scenario, while the discovered rules AND the solved per-axis
# strategies stay byte-identical to the unpruned (seed-behavior) sweep
if python -c "import jax" >/dev/null 2>&1; then
    echo "== bench.py --discovery (pruned ShardCombine discovery gate)"
    out=$(python bench.py --discovery 2>/dev/null) || rc=1
    echo "$out"
    verdict=$(python - "$out" <<'PYEOF'
import json, sys
try:
    r = json.loads(sys.argv[1].strip().splitlines()[-1])
    if "error" in r:
        print("error: " + r["error"])
    elif r.get("ratio_cold", 0) < 5.0:
        print(f"cold probe reduction {r.get('ratio_cold')}x < 5x "
              f"({r.get('probes_cold')} vs {r.get('probes_baseline')} "
              f"baseline)")
    elif r.get("ratio_warm", 0) < 10.0:
        print(f"warm probe reduction {r.get('ratio_warm')}x < 10x "
              f"({r.get('probes_warm')} vs {r.get('probes_baseline')} "
              f"baseline)")
    elif not r.get("rules_equal"):
        print("pruned discovery rules diverge from the unpruned sweep")
    elif not r.get("strategies_equal"):
        print("pruned solver strategies diverge from the unpruned sweep")
    elif r.get("perf_regression"):
        print(f"committed-floor regression: {r.get('value')} is >10% below "
              f"last-good {r.get('last_good_value')}")
    else:
        print("ok")
except Exception as e:
    print(f"unparseable: {e}")
PYEOF
)
    if [ "$verdict" != "ok" ]; then
        echo "static_checks: discovery gate failed ($verdict)"
        rc=1
    fi
else
    echo "static_checks: jax not importable; skipping bench.py --discovery"
fi

[ "$ran" = 0 ] && echo "static_checks: no external linters ran (configs still validated by CI tests)"
exit $rc
